"""Server runtime tests: the full async scheduling loop, multi-server raft,
heartbeat failure recovery, blocked-eval unblocking."""
import time

import pytest

from nomad_tpu import mock
from nomad_tpu.server import InProcRaft, Server, ServerConfig
from nomad_tpu.structs.structs import (
    ALLOC_CLIENT_RUNNING,
    ALLOC_DESIRED_RUN,
    EVAL_STATUS_BLOCKED,
    EVAL_STATUS_COMPLETE,
    NODE_STATUS_DOWN,
)


@pytest.fixture
def server():
    s = Server(ServerConfig(num_schedulers=2, deterministic=True,
                            scheduler_algorithm="binpack"))
    s.start()
    yield s
    s.stop()


def wait_for(cond, timeout=10.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(0.02)
    raise AssertionError(f"timed out waiting for {msg}")


def test_end_to_end_job_schedule(server):
    for _ in range(5):
        server.register_node(mock.node())
    job = mock.job()
    job.task_groups[0].count = 5
    eval_id = server.register_job(job)

    wait_for(
        lambda: len([
            a for a in server.fsm.state.allocs_by_job(job.namespace, job.id, True)
            if a.desired_status == ALLOC_DESIRED_RUN
        ]) == 5,
        msg="5 allocs placed",
    )
    ev = server.fsm.state.eval_by_id(eval_id)
    wait_for(lambda: server.fsm.state.eval_by_id(eval_id).status == EVAL_STATUS_COMPLETE,
             msg="eval complete")
    allocs = server.fsm.state.allocs_by_job(job.namespace, job.id, True)
    assert len({a.node_id for a in allocs}) == 5  # anti-affinity spread


def test_scale_up_and_down(server):
    for _ in range(6):
        server.register_node(mock.node())
    job = mock.job()
    job.task_groups[0].count = 3
    server.register_job(job)
    wait_for(lambda: len(server.fsm.state.allocs_by_job(job.namespace, job.id, True)) == 3,
             msg="initial 3")

    job2 = job.copy()
    job2.task_groups[0].count = 6
    server.register_job(job2)
    wait_for(
        lambda: len([
            a for a in server.fsm.state.allocs_by_job(job.namespace, job.id, True)
            if a.desired_status == ALLOC_DESIRED_RUN
        ]) == 6,
        msg="scaled to 6",
    )

    job3 = job.copy()
    job3.task_groups[0].count = 2
    server.register_job(job3)
    wait_for(
        lambda: len([
            a for a in server.fsm.state.allocs_by_job(job.namespace, job.id, True)
            if a.desired_status == ALLOC_DESIRED_RUN
        ]) == 2,
        msg="scaled to 2",
    )


def test_blocked_eval_unblocks_on_capacity(server):
    # No nodes: placement fails, eval blocks
    job = mock.job()
    job.task_groups[0].count = 2
    server.register_job(job)
    wait_for(lambda: server.blocked_evals.stats()["total_blocked"] >= 1,
             msg="eval blocked")
    assert server.fsm.state.allocs_by_job(job.namespace, job.id, True) == []

    # Capacity appears: blocked eval re-runs and places
    server.register_node(mock.node())
    server.register_node(mock.node())
    wait_for(
        lambda: len(server.fsm.state.allocs_by_job(job.namespace, job.id, True)) == 2,
        msg="unblocked placement",
    )


def test_heartbeat_failure_reschedules():
    server = Server(ServerConfig(num_schedulers=2, deterministic=True,
                                 scheduler_algorithm="binpack",
                                 heartbeat_min_ttl=0.3, heartbeat_max_ttl=0.5))
    server.start()
    nodes = [mock.node() for _ in range(3)]
    ttls = [server.register_node(n) for n in nodes]
    assert all(0.3 <= t <= 0.5 for t in ttls)
    job = mock.job()
    job.task_groups[0].count = 1
    job.task_groups[0].reschedule_policy.delay_ns = 0
    server.register_job(job)

    def placed_keeping_alive():
        for n in nodes:
            server.heartbeat(n.id)
        return len(server.fsm.state.allocs_by_job(job.namespace, job.id, True)) == 1

    wait_for(placed_keeping_alive, msg="placed")
    alloc = server.fsm.state.allocs_by_job(job.namespace, job.id, True)[0]
    first_node = alloc.node_id

    # mark running on client, then stop heartbeating ONLY that node
    ca = alloc.copy_skip_job()
    ca.client_status = ALLOC_CLIENT_RUNNING
    server.update_allocs_from_client([ca])
    hb_nodes = [n for n in nodes if n.id != first_node]

    deadline = time.monotonic() + 8
    replaced = []

    def check():
        for n in hb_nodes:
            server.heartbeat(n.id)
        node = server.fsm.state.node_by_id(first_node)
        if node.status != NODE_STATUS_DOWN:
            return False
        live = [
            a for a in server.fsm.state.allocs_by_job(job.namespace, job.id, True)
            if a.desired_status == ALLOC_DESIRED_RUN and not a.terminal_status()
        ]
        replaced[:] = live
        return len(live) == 1 and live[0].node_id != first_node

    try:
        wait_for(check, timeout=10, msg="alloc replaced off dead node")
        # lost-node replacements are fresh placements (reference semantics:
        # only migrate/reschedule placements chain previous_allocation)
        assert replaced[0].id != alloc.id
        stopped = [
            a for a in server.fsm.state.allocs_by_job(job.namespace, job.id, True)
            if a.id == alloc.id
        ]
        assert stopped and stopped[0].client_status == "lost"
    finally:
        server.stop()


def test_multi_server_replication_and_failover():
    raft = InProcRaft()
    cfg = ServerConfig(num_schedulers=1, deterministic=True, scheduler_algorithm="binpack")
    s1 = Server(cfg, raft=raft, name="s1")
    s2 = Server(cfg, raft=raft, name="s2")
    s3 = Server(cfg, raft=raft, name="s3")
    for s in (s1, s2, s3):
        s.start()
    try:
        assert s1.is_leader and not s2.is_leader

        for _ in range(3):
            s1.register_node(mock.node())
        job = mock.job()
        job.task_groups[0].count = 3
        s1.register_job(job)
        wait_for(lambda: len(s1.fsm.state.allocs_by_job(job.namespace, job.id, True)) == 3,
                 msg="leader placed")
        # replicated to followers
        assert len(s2.fsm.state.allocs_by_job(job.namespace, job.id, True)) == 3
        assert len(s3.fsm.state.allocs_by_job(job.namespace, job.id, True)) == 3

        # failover: s2 takes leadership, can schedule new work
        raft.transfer_leadership(s2.peer)
        assert s2.is_leader and not s1.is_leader
        job2 = mock.job()
        job2.task_groups[0].count = 2
        s2.register_job(job2)
        wait_for(lambda: len(s2.fsm.state.allocs_by_job(job2.namespace, job2.id, True)) == 2,
                 msg="new leader placed")
        assert len(s1.fsm.state.allocs_by_job(job2.namespace, job2.id, True)) == 2
    finally:
        for s in (s1, s2, s3):
            s.stop()


def test_plan_rejection_on_stale_state():
    """Two plans racing for the same capacity: the applier rejects the loser."""
    from nomad_tpu.structs.structs import (
        AllocatedResources,
        AllocatedTaskResources,
        Allocation,
        Plan,
    )

    s = Server(ServerConfig(num_schedulers=0, scheduler_algorithm="binpack"))
    s.start()
    try:
        node = mock.node()  # 4000 MHz, 100 reserved
        s.register_node(node)

        def make_plan(cpu):
            job = mock.job()
            plan = Plan(priority=50, job=job)
            alloc = Allocation(
                node_id=node.id, job_id=job.id, task_group="web",
                allocated_resources=AllocatedResources(
                    tasks={"web": AllocatedTaskResources(cpu_shares=cpu, memory_mb=64)}
                ),
            )
            plan.node_allocation[node.id] = [alloc]
            return plan

        p1 = s.plan_queue.enqueue(make_plan(3000))
        r1 = p1.future.result(timeout=5)
        assert len(r1.node_allocation) == 1  # fits

        p2 = s.plan_queue.enqueue(make_plan(3000))
        r2 = p2.future.result(timeout=5)
        # 3000 + 3000 + 100 reserved > 4000: rejected, refresh forced
        assert len(r2.node_allocation) == 0
        assert r2.refresh_index > 0
    finally:
        s.stop()


def test_deregister_job_stops_allocs(server):
    for _ in range(3):
        server.register_node(mock.node())
    job = mock.job()
    job.task_groups[0].count = 3
    server.register_job(job)
    wait_for(lambda: len(server.fsm.state.allocs_by_job(job.namespace, job.id, True)) == 3,
             msg="placed")
    server.deregister_job(job.namespace, job.id)
    wait_for(
        lambda: all(
            a.desired_status != ALLOC_DESIRED_RUN
            for a in server.fsm.state.allocs_by_job(job.namespace, job.id, True)
        ),
        msg="all stopped",
    )


def test_failed_eval_reaped_and_followed_up():
    """An eval that exhausts its delivery limit lands in _failed and the
    leader reaper marks it failed + creates a follow-up."""
    s = Server(ServerConfig(num_schedulers=0, scheduler_algorithm="binpack",
                            unblock_failed_interval=0.2))
    s.start()
    try:
        s.eval_broker.delivery_limit = 1
        s.eval_broker.initial_nack_delay = 0.01
        s.eval_broker.subsequent_nack_delay = 0.01
        ev = mock.eval()
        s.raft_apply("eval-update", [ev])
        # dequeue + nack once: with delivery_limit=1 it goes to _failed
        got, token = s.eval_broker.dequeue(["service"], timeout=2)
        assert got is not None
        s.eval_broker.nack(got.id, token)
        wait_for(
            lambda: s.fsm.state.eval_by_id(ev.id) is not None
            and s.fsm.state.eval_by_id(ev.id).status == "failed",
            timeout=5, msg="eval reaped as failed",
        )
        reaped = s.fsm.state.eval_by_id(ev.id)
        assert reaped.next_eval  # follow-up chained
        assert s.fsm.state.eval_by_id(reaped.next_eval) is not None
    finally:
        s.stop()


def test_block_after_missed_unblock_reenqueues():
    """An eval blocking against a stale snapshot re-enqueues immediately if
    capacity appeared since (reference missedUnblock)."""
    s = Server(ServerConfig(num_schedulers=0, scheduler_algorithm="binpack"))
    s.start()
    try:
        n = mock.node()
        s.register_node(n)  # capacity change at some index
        ev = mock.eval()
        ev.snapshot_index = 0  # older than the node registration
        ev.status = EVAL_STATUS_BLOCKED
        s.blocked_evals.block(ev)
        # not captured: re-enqueued to the broker instead
        assert s.blocked_evals.stats()["total_blocked"] == 0
        got, token = s.eval_broker.dequeue(["service"], timeout=2)
        assert got is not None and got.id == ev.id
        s.eval_broker.ack(got.id, token)
    finally:
        s.stop()


def test_reblock_while_outstanding_requeues_after_ack():
    """An unblock racing a worker's in-flight reblock must not drop the eval.

    The worker reblocks an eval while it is still unacked in the broker; a
    capacity change then unblocks it before the ack lands. The token carried
    through BlockedEvals routes the re-enqueue via the broker's
    requeue-after-ack path (reference wrappedEval + EnqueueAll semantics).
    """
    from nomad_tpu.server.eval_broker import EvalBroker
    from nomad_tpu.server.blocked_evals import BlockedEvals
    from nomad_tpu.structs.structs import EVAL_STATUS_BLOCKED as _BLK

    broker = EvalBroker()
    broker.set_enabled(True)
    blocked = BlockedEvals(broker)
    blocked.set_enabled(True)

    ev = mock.eval()
    ev.class_eligibility = {"c1": True}
    broker.enqueue(ev)
    out, token = broker.dequeue([ev.type], timeout=1.0)
    assert out is not None and out.id == ev.id

    # Leader ordering: the raft apply fires the FSM eval-upsert hook first,
    # capturing the eval with no token...
    reblocked = ev.copy()
    reblocked.status = _BLK
    blocked.block(reblocked)
    # ...then the worker's reblock records its delivery token on the entry.
    blocked.reblock(reblocked, token)
    assert blocked.tokens[ev.id] == token

    # Capacity change unblocks while the eval is still unacked: without the
    # token this enqueue is silently dropped as a duplicate.
    blocked.unblock("c1", index=100)
    assert broker.stats()["total_ready"] == 0  # parked behind the ack

    broker.ack(ev.id, token)
    # The requeued copy is now deliverable again.
    out2, token2 = broker.dequeue([ev.type], timeout=1.0)
    assert out2 is not None and out2.id == ev.id
    assert out2.snapshot_index == 100
    broker.ack(ev.id, token2)


def test_deployment_alloc_health_counts_are_idempotent():
    """Duplicate health reports must not inflate deployment counters, and a
    healthy->unhealthy flip must move the count, not double-book it."""
    from nomad_tpu.server.fsm import DEPLOYMENT_ALLOC_HEALTH, NomadFSM
    from nomad_tpu.structs.structs import Deployment, DeploymentState

    fsm = NomadFSM()
    node = mock.node()
    fsm.state.upsert_node(1, node)
    job = mock.job()
    fsm.state.upsert_job(2, job)
    alloc = mock.alloc()
    alloc.namespace, alloc.job_id, alloc.job = job.namespace, job.id, job
    alloc.node_id = node.id
    alloc.task_group = job.task_groups[0].name
    fsm.state.upsert_allocs(3, [alloc])

    d = Deployment(
        job_id=job.id,
        namespace=job.namespace,
        job_version=job.version,
        task_groups={job.task_groups[0].name: DeploymentState(desired_total=1)},
        status="running",
    )
    fsm.state.upsert_deployment(4, d)
    alloc.deployment_id = d.id
    fsm.state.upsert_allocs(4, [alloc])

    # A report for an alloc of a different deployment must be ignored.
    other = mock.alloc()
    other.namespace, other.job_id, other.job = job.namespace, job.id, job
    other.node_id, other.task_group = node.id, job.task_groups[0].name
    other.deployment_id = "some-other-deployment"
    fsm.state.upsert_allocs(4, [other])

    def health(idx, healthy_ids, unhealthy_ids):
        fsm.apply(idx, DEPLOYMENT_ALLOC_HEALTH,
                  (d.id, healthy_ids, unhealthy_ids, 0, None, None))

    health(5, [alloc.id], [])
    health(6, [alloc.id], [])  # duplicate report
    health(6, [], [other.id])  # other deployment's alloc: ignored
    ds = fsm.state.deployment_by_id(d.id).task_groups[alloc.task_group]
    assert ds.healthy_allocs == 1
    assert ds.unhealthy_allocs == 0

    health(7, [], [alloc.id])  # flip
    ds = fsm.state.deployment_by_id(d.id).task_groups[alloc.task_group]
    assert ds.healthy_allocs == 0
    assert ds.unhealthy_allocs == 1


def test_client_sync_without_health_preserves_counters():
    """A status sync carrying no deployment_status must not erase recorded
    health — otherwise a later re-report double-counts healthy_allocs."""
    from nomad_tpu.server.fsm import DEPLOYMENT_ALLOC_HEALTH, NomadFSM
    from nomad_tpu.structs.structs import Deployment, DeploymentState

    fsm = NomadFSM()
    node = mock.node()
    fsm.state.upsert_node(1, node)
    job = mock.job()
    fsm.state.upsert_job(2, job)
    alloc = mock.alloc()
    alloc.namespace, alloc.job_id, alloc.job = job.namespace, job.id, job
    alloc.node_id = node.id
    alloc.task_group = job.task_groups[0].name
    d = Deployment(
        job_id=job.id,
        namespace=job.namespace,
        job_version=job.version,
        task_groups={alloc.task_group: DeploymentState(desired_total=2)},
        status="running",
    )
    fsm.state.upsert_deployment(3, d)
    alloc.deployment_id = d.id
    fsm.state.upsert_allocs(4, [alloc])

    fsm.apply(5, DEPLOYMENT_ALLOC_HEALTH, (d.id, [alloc.id], [], 0, None, None))
    assert fsm.state.deployment_by_id(d.id).task_groups[alloc.task_group].healthy_allocs == 1

    # plain client sync with no deployment_status
    sync = alloc.copy_skip_job()
    sync.client_status = ALLOC_CLIENT_RUNNING
    sync.deployment_status = None
    fsm.state.update_allocs_from_client(6, [sync])
    stored = fsm.state.alloc_by_id(alloc.id)
    assert stored.deployment_status is not None and stored.deployment_status.healthy is True

    # duplicate health report must still be a no-op
    fsm.apply(7, DEPLOYMENT_ALLOC_HEALTH, (d.id, [alloc.id], [], 0, None, None))
    assert fsm.state.deployment_by_id(d.id).task_groups[alloc.task_group].healthy_allocs == 1


def test_node_capacity_event_racing_block_is_not_lost():
    """unblock_node firing between eval creation and block() must be caught
    by the missed-unblock witness (system-scheduler analog of the class
    capacity race)."""
    from nomad_tpu.server.blocked_evals import BlockedEvals
    from nomad_tpu.server.eval_broker import EvalBroker
    from nomad_tpu.structs.structs import Evaluation

    broker = EvalBroker()
    broker.set_enabled(True)
    blocked = BlockedEvals(broker)
    blocked.set_enabled(True)

    ev = Evaluation(type="system", job_id="sysjob", node_id="node-1",
                    status=EVAL_STATUS_BLOCKED, snapshot_index=10)
    # capacity appears on the node AFTER the eval's snapshot but BEFORE block()
    blocked.unblock_node("node-1", 12)
    blocked.block(ev)
    # the eval must have been re-enqueued, not left blocked
    assert blocked.stats()["total_blocked"] == 0
    dequeued, token = broker.dequeue(["system"], timeout=1.0)
    assert dequeued is not None and dequeued.job_id == "sysjob"
