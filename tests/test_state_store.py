"""State store tests, mirroring reference nomad/state/state_store_test.go
core behaviors: index stamping, snapshot isolation, blocking queries, job
versioning, secondary indexes, client-owned field preservation, plan
result application (deployment counters), and periodic launches.
"""
import threading
import time

import pytest

from nomad_tpu import mock
from nomad_tpu.state import StateStore
from nomad_tpu.structs.structs import (
    ALLOC_CLIENT_RUNNING,
    AllocDeploymentStatus,
    Deployment,
    DeploymentState,
)


class TestIndexes:
    def test_upserts_stamp_indexes(self):
        s = StateStore()
        n = mock.node()
        s.upsert_node(10, n)
        stored = s.node_by_id(n.id)
        assert stored.create_index == 10 and stored.modify_index == 10
        n2 = stored.copy()
        n2.name = "renamed"
        s.upsert_node(11, n2)
        stored = s.node_by_id(n.id)
        assert stored.create_index == 10 and stored.modify_index == 11
        assert s.latest_index == 11

    def test_latest_index_monotonic(self):
        s = StateStore()
        s.upsert_node(50, mock.node())
        s.upsert_node(20, mock.node())  # lower index must not regress
        assert s.latest_index == 50


class TestSnapshotIsolation:
    def test_writes_invisible_to_snapshot(self):
        s = StateStore()
        n1 = mock.node()
        s.upsert_node(1, n1)
        snap = s.snapshot()
        n2 = mock.node()
        s.upsert_node(2, n2)
        assert snap.node_by_id(n2.id) is None
        assert len(snap.nodes()) == 1
        assert len(s.nodes()) == 2

    def test_snapshot_min_index_waits(self):
        s = StateStore()
        s.upsert_node(1, mock.node())

        def writer():
            time.sleep(0.15)
            s.upsert_node(5, mock.node())

        t = threading.Thread(target=writer)
        t.start()
        snap = s.snapshot_min_index(5, timeout=5)
        t.join()
        assert snap.latest_index >= 5

    def test_blocking_query_wakes_on_write(self):
        s = StateStore()
        s.upsert_node(1, mock.node())

        def writer():
            time.sleep(0.1)
            s.upsert_node(2, mock.node())

        t = threading.Thread(target=writer)
        t.start()
        t0 = time.monotonic()
        nodes, index = s.blocking_query(lambda st: st.nodes(), min_index=1,
                                        timeout=5)
        t.join()
        assert index >= 2 and len(nodes) == 2
        assert time.monotonic() - t0 < 4, "must wake on write, not timeout"


class TestJobs:
    def test_job_versions_retained(self):
        s = StateStore()
        job = mock.job()
        s.upsert_job(1, job)
        j2 = job.copy()
        j2.version = 0  # store assigns versions
        j2.meta = {"rev": "2"}
        s.upsert_job(2, j2)
        versions = s.job_versions.get(("default", job.id), [])
        assert len(versions) >= 2
        current = s.job_by_id("default", job.id)
        old = s.job_by_id_and_version("default", job.id, current.version - 1)
        assert old is not None

    def test_jobs_by_parent_index(self):
        s = StateStore()
        parent = mock.job()
        s.upsert_job(1, parent)
        child = mock.job()
        child.parent_id = parent.id
        s.upsert_job(2, child)
        kids = s.jobs_by_parent("default", parent.id)
        assert [j.id for j in kids] == [child.id]
        s.delete_job(3, "default", child.id)
        assert s.jobs_by_parent("default", parent.id) == []


class TestAllocs:
    def test_secondary_indexes(self):
        s = StateStore()
        job = mock.job()
        a = mock.alloc()
        a.job = job
        a.job_id = job.id
        s.upsert_allocs(5, [a])
        assert [x.id for x in s.allocs_by_node(a.node_id)] == [a.id]
        assert [x.id for x in s.allocs_by_job("default", job.id, True)] == [a.id]
        assert [x.id for x in s.allocs_by_eval(a.eval_id)] == [a.id]

    def test_client_fields_preserved_on_server_update(self):
        """A server-side upsert with empty client_status must not clobber
        the client's reported status (state_store.go UpsertAllocs COMPAT)."""
        s = StateStore()
        a = mock.alloc()
        s.upsert_allocs(1, [a])
        client_view = a.copy_skip_job()
        client_view.client_status = ALLOC_CLIENT_RUNNING
        s.update_allocs_from_client(2, [client_view])
        server_view = s.alloc_by_id(a.id).copy_skip_job()
        server_view.client_status = ""
        s.upsert_allocs(3, [server_view])
        assert s.alloc_by_id(a.id).client_status == ALLOC_CLIENT_RUNNING


class TestPlanResults:
    def test_deployment_counters_on_plan_apply(self):
        """upsert_plan_results counts NEW deployment placements once —
        in-place updates of already-counted allocs must not inflate
        (state_store.go updateDeploymentWithAlloc)."""
        s = StateStore()
        job = mock.job()
        d = Deployment(namespace="default", job_id=job.id, status="running")
        d.task_groups["web"] = DeploymentState(desired_total=2)
        a = mock.alloc()
        a.job = job
        a.job_id = job.id
        a.deployment_id = d.id
        s.upsert_plan_results(
            10, alloc_updates=[a], allocs_stopped=[], allocs_preempted=[],
            deployment=d,
        )
        assert s.deployment_by_id(d.id).task_groups["web"].placed_allocs == 1
        # re-upsert the SAME alloc (in-place update): no double count
        a2 = s.alloc_by_id(a.id).copy_skip_job()
        s.upsert_plan_results(
            11, alloc_updates=[a2], allocs_stopped=[], allocs_preempted=[],
        )
        assert s.deployment_by_id(d.id).task_groups["web"].placed_allocs == 1

    def test_update_deployment_alloc_health(self):
        s = StateStore()
        job = mock.job()
        d = Deployment(namespace="default", job_id=job.id, status="running")
        d.task_groups["web"] = DeploymentState(desired_total=1)
        a = mock.alloc()
        a.job = job
        a.job_id = job.id
        a.deployment_id = d.id
        s.upsert_plan_results(
            10, alloc_updates=[a], allocs_stopped=[], allocs_preempted=[],
            deployment=d,
        )
        s.update_deployment_alloc_health(11, d.id, [a.id], [], 123)
        assert s.deployment_by_id(d.id).task_groups["web"].healthy_allocs == 1
        stored = s.alloc_by_id(a.id)
        assert stored.deployment_status.healthy is True


class TestPeriodic:
    def test_periodic_launch_table(self):
        s = StateStore()
        s.upsert_periodic_launch(5, "default", "cron-job", 999)
        assert s.periodic_launch_table[("default", "cron-job")] == 999
