"""nomad-lint (nomad_tpu/analysis): the repo's invariants, enforced in tier-1.

Two layers:

  1. The whole-tree gate: every checker over ``nomad_tpu/`` must report
     zero findings beyond the shipped baseline — this is the same pass
     ``python -m nomad_tpu.analysis`` runs, so CI needs no extra plumbing.
  2. Fixture units per checker: a positive (the exact bug-shaped pattern
     each satellite fix removed — reverting a fix re-creates it) and a
     negative (the fixed shape) per rule, plus suppression/baseline
     mechanics.

Plus behavioral regressions for the two engine fixes a linter can't see
structurally: the single-flight claim release on unexpected exceptions,
and the stale-claim waiter-cohort wakeup.
"""
import json
import os
import textwrap
import threading
import time

import pytest

from nomad_tpu.analysis import (
    Finding,
    apply_baseline,
    load_baseline,
    run_paths,
    run_source,
)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO_ROOT, "nomad_tpu")
BASELINE = os.path.join(PKG, "analysis", "baseline.json")


def dedent(s: str) -> str:
    return textwrap.dedent(s).lstrip("\n")


# ---------------------------------------------------------------------------
# 1. the tree gate
# ---------------------------------------------------------------------------


def test_tree_is_clean_modulo_baseline():
    """`python -m nomad_tpu.analysis nomad_tpu/` semantics: zero
    non-baselined findings across the whole package."""
    findings = run_paths([PKG], rel_to=REPO_ROOT)
    baseline = load_baseline(BASELINE) if os.path.exists(BASELINE) else []
    new, _stale = apply_baseline(findings, baseline)
    assert new == [], "\n".join(f.render() for f in new)


def test_cli_module_exits_zero():
    import subprocess
    import sys

    proc = subprocess.run(
        [sys.executable, "-m", "nomad_tpu.analysis", "nomad_tpu"],
        cwd=REPO_ROOT, capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr


# ---------------------------------------------------------------------------
# 2. fixture units — dtype-discipline
# ---------------------------------------------------------------------------


def test_dtype_flags_uncast_int64_subtraction():
    # the exact epoch_usage_arrays bug shape (reverting the encode.py
    # satellite fix re-creates this finding)
    src = dedent("""
        import numpy as np
        def epoch_usage_arrays(fleet, n_pad, n_real, fdtype):
            totals4 = fleet["totals4"]
            reserved4 = fleet["reserved4"]
            node_c2 = np.zeros((n_pad, 2), np.int64)
            node_c2[:n_real] = (totals4[:, :2] - reserved4[:, :2]).astype(np.int64)
            return node_c2
    """)
    fs = run_source(src, "tpu/encode.py")
    assert [f.rule for f in fs] == ["dtype-discipline"]
    assert "int64 cast of a subtraction" in fs[0].message


def test_dtype_accepts_percast_operands():
    # the fixed shape: each operand cast to the eval dtype first
    src = dedent("""
        import numpy as np
        def epoch_usage_arrays(fleet, n_pad, n_real, fdtype):
            totals4 = fleet["totals4"]
            reserved4 = fleet["reserved4"]
            node_c2 = np.zeros((n_pad, 2), np.int64)
            node_c2[:n_real] = (
                totals4[:, :2].astype(fdtype) - reserved4[:, :2].astype(fdtype)
            ).astype(np.int64)
            return node_c2
    """)
    assert run_source(src, "tpu/encode.py") == []


def test_dtype_flags_float64_allocation_arithmetic():
    src = dedent("""
        import numpy as np
        def f(x):
            buf = np.zeros((4, 4), dtype=np.float64)
            return buf - x
    """)
    fs = run_source(src, "tpu/intscore.py")
    assert [f.rule for f in fs] == ["dtype-discipline"]
    assert "float64 operand" in fs[0].message


def test_packed_lane_flags_raw_bit_unpack():
    # hand-rolled unpack of a packed plane in a consumer module (the
    # scan step) must go through the blessed intscore helpers
    src = dedent("""
        import jax.numpy as jnp
        def step(static):
            feat_packed = static[3]
            feas = (feat_packed >> 0) & 1
            return feas
    """)
    fs = run_source(src, "tpu/engine.py")
    assert [f.rule for f in fs] == ["dtype-discipline"]
    assert "raw bit unpack" in fs[0].message
    assert "feat_packed" in fs[0].message


def test_packed_lane_accepts_blessed_helpers():
    # the helpers themselves are the sanctioned bit surgery — both their
    # definitions and calls through them are clean
    src = dedent("""
        import jax.numpy as jnp
        def unpack_feat_lane(packed, bit):
            return ((packed >> bit) & 1).astype(bool)
        def step(static):
            feat_packed = static[3]
            return unpack_feat_lane(feat_packed, 0)
    """)
    assert run_source(src, "tpu/engine.py") == []


def test_packed_lane_flags_float_promotion():
    src = dedent("""
        import numpy as np
        def bad_cast(feat_packed):
            return feat_packed.astype(np.float32)
        def bad_arith(count_packed):
            return count_packed * 0.5
    """)
    fs = run_source(src, "tpu/batcher.py")
    assert [f.rule for f in fs] == ["dtype-discipline"] * 2
    assert "float promotion" in fs[0].message
    assert "float promotion" in fs[1].message


def test_packed_lane_scoped_to_kernel_modules():
    # packed-named arrays elsewhere (host code, tests) are not the
    # kernel's lane layout; no findings outside the packed target list
    src = dedent("""
        def f(msg_packed):
            return (msg_packed >> 8) & 0xFF
    """)
    assert run_source(src, "server/worker.py") == []


def test_dtype_scoped_to_parity_modules():
    # the same pattern outside encode/intscore is host-path float64 by
    # design and not flagged
    src = dedent("""
        import numpy as np
        def f(a, b):
            return (a - b).astype(np.int64)
    """)
    assert run_source(src, "server/worker.py") == []


# ---------------------------------------------------------------------------
# fixture units — shared-state-discipline (guarded-by path)
# ---------------------------------------------------------------------------

BATCHER_DECL = dedent("""
    import threading
    class DeviceBatcher:
        def __init__(self):
            self._lock = threading.Lock()
            self.stats = {"dispatches": 0}  # guarded-by: _lock
""")


def test_lock_flags_unguarded_cross_module_write():
    # the exact run_forced bug shape (reverting the engine.py satellite
    # fix re-creates this finding)
    src = dedent("""
        def compute_system_placements(batcher):
            batcher.stats["dispatches"] = batcher.stats.get("dispatches", 0) + 1
    """)
    fs = run_source(src, "tpu/engine.py",
                    extra_modules=[(BATCHER_DECL, "tpu/batcher.py")])
    assert [f.rule for f in fs] == ["shared-state-discipline"]
    assert "batcher.stats" in fs[0].message


def test_lock_accepts_with_lock_write():
    src = dedent("""
        def compute_system_placements(batcher):
            with batcher._lock:
                batcher.stats["dispatches"] = batcher.stats.get("dispatches", 0) + 1
    """)
    assert run_source(src, "tpu/engine.py",
                      extra_modules=[(BATCHER_DECL, "tpu/batcher.py")]) == []


def test_lock_flags_self_write_in_declaring_class():
    # the annotated declaration itself is exempt
    assert run_source(BATCHER_DECL, "tpu/batcher.py") == []

    src2 = dedent("""
        import threading
        class DeviceBatcher:
            def __init__(self):
                self._lock = threading.Lock()
                self.stats = {"d": 0}  # guarded-by: _lock
            def _run_batch(self):
                self.stats["d"] += 1
            def _run_batch_locked(self):
                with self._lock:
                    self.stats["d"] += 1
    """)
    fs = run_source(src2, "tpu/batcher.py")
    assert len(fs) == 1 and fs[0].rule == "shared-state-discipline"
    assert fs[0].line == 7


def test_lock_ignores_unannotated_same_name_attr():
    # worker.py has its own self.stats with no annotation: self-writes in
    # a NON-declaring class are not flagged
    src = dedent("""
        class Worker:
            def __init__(self):
                self.stats = {"evals_processed": 0}
            def run(self):
                self.stats["evals_processed"] += 1
    """)
    fs = run_source(src, "server/worker.py",
                    extra_modules=[(BATCHER_DECL, "tpu/batcher.py")])
    assert fs == []


# ---------------------------------------------------------------------------
# fixture units — shared-state-discipline (inferred-sharing path)
# ---------------------------------------------------------------------------


def test_shared_state_flags_unguarded_write_from_two_roots():
    src = dedent("""
        import threading

        class Broker:
            def __init__(self):
                self._lock = threading.Lock()
                self.pending = {}
                threading.Thread(target=self._pump, daemon=True).start()
                threading.Thread(target=self._drain, daemon=True).start()

            def _pump(self):
                self.pending["a"] = 1

            def _drain(self):
                self.pending.pop("a", None)
    """)
    fs = run_source(src, "server/brokerfix.py")
    hits = [f for f in fs if f.rule == "shared-state-discipline"]
    assert hits, fs
    assert any("Broker.pending" in f.message
               and "concurrent roots" in f.message for f in hits)


def test_shared_state_accepts_lexically_held_writes():
    src = dedent("""
        import threading

        class Broker:
            def __init__(self):
                self._lock = threading.Lock()
                self.pending = {}
                threading.Thread(target=self._pump, daemon=True).start()
                threading.Thread(target=self._drain, daemon=True).start()

            def _pump(self):
                with self._lock:
                    self.pending["a"] = 1

            def _drain(self):
                with self._lock:
                    self.pending.pop("a", None)
    """)
    assert run_source(src, "server/brokerfix.py") == []


def test_shared_state_all_call_sites_held_proof():
    # _bump never takes the lock itself; every call site does, which the
    # interprocedural proof accepts
    src = dedent("""
        import threading

        class Broker:
            def __init__(self):
                self._lock = threading.Lock()
                self.pending = {}
                threading.Thread(target=self._pump, daemon=True).start()
                threading.Thread(target=self._drain, daemon=True).start()

            def _pump(self):
                with self._lock:
                    self._bump()

            def _drain(self):
                with self._lock:
                    self._bump()

            def _bump(self):
                self.pending["n"] = 1
    """)
    assert run_source(src, "server/brokerfix.py") == []


def test_shared_state_race_ok_suppresses_with_reason():
    src = dedent("""
        import threading

        class Broker:
            def __init__(self):
                self._lock = threading.Lock()
                self.hits = []
                threading.Thread(target=self._pump, daemon=True).start()
                threading.Thread(target=self._drain, daemon=True).start()

            def _pump(self):
                self.hits.append(1)  # race-ok: GIL-atomic append, read at join

            def _drain(self):
                self.hits.append(2)  # race-ok: GIL-atomic append, read at join
    """)
    assert run_source(src, "server/brokerfix.py") == []


def test_shared_state_race_ok_requires_reason():
    src = dedent("""
        import threading

        class Broker:
            def __init__(self):
                self._lock = threading.Lock()
                self.hits = []
                threading.Thread(target=self._pump, daemon=True).start()
                threading.Thread(target=self._drain, daemon=True).start()

            def _pump(self):
                self.hits.append(1)  # race-ok:

            def _drain(self):
                self.hits.append(2)  # race-ok: GIL-atomic append
    """)
    fs = run_source(src, "server/brokerfix.py")
    assert len(fs) == 1
    assert "needs a reason" in fs[0].message


def test_shared_state_stale_race_ok_fails():
    # a race-ok that suppresses nothing is itself a finding: the ratchet
    # only tightens
    src = dedent("""
        import threading

        class Broker:
            def __init__(self):
                self._lock = threading.Lock()
                self.hits = []  # race-ok: nothing here needs suppressing

            def _pump(self):
                with self._lock:
                    self.hits.append(1)
    """)
    fs = run_source(src, "server/brokerfix.py")
    assert len(fs) == 1
    assert "stale '# race-ok'" in fs[0].message


def test_shared_state_immutable_after_init_is_clean():
    # construction-path writes (__init__ and helpers called only from
    # it) happen-before publication
    src = dedent("""
        import threading

        class Broker:
            def __init__(self):
                self._lock = threading.Lock()
                self.pending = {}
                self._load()
                threading.Thread(target=self._pump, daemon=True).start()
                threading.Thread(target=self._drain, daemon=True).start()

            def _load(self):
                self.pending["seed"] = 0

            def _pump(self):
                with self._lock:
                    self.pending["a"] = 1

            def _drain(self):
                with self._lock:
                    self.pending.pop("a", None)
    """)
    assert run_source(src, "server/brokerfix.py") == []


# ---------------------------------------------------------------------------
# fixture units — jit-purity
# ---------------------------------------------------------------------------


def test_jit_flags_impure_call_in_decorated_fn():
    src = dedent("""
        import jax, time
        @jax.jit
        def f(x):
            t = time.time()
            return x
    """)
    fs = run_source(src, "tpu/kernels.py")
    assert [f.rule for f in fs] == ["jit-purity"]
    assert "time.time" in fs[0].message


def test_jit_flags_transitive_callee_and_jit_call_form():
    # the engine's builder pattern: jax.jit(fn) on a closure that calls a
    # same-module helper
    src = dedent("""
        import jax
        import numpy as np
        def _make_step():
            def helper(c):
                print("debug", c)
                return c
            def step(c, x):
                return helper(c), x
            return step
        def build():
            step = _make_step()
            return jax.jit(step)
    """)
    fs = run_source(src, "tpu/kernels.py")
    assert [f.rule for f in fs] == ["jit-purity"]
    assert "print" in fs[0].message


def test_jit_flags_partial_jit_and_global_mutation():
    src = dedent("""
        import jax
        from functools import partial
        COUNTER = 0
        @partial(jax.jit, static_argnames=("n",))
        def f(n, x):
            global COUNTER
            COUNTER += 1
            return x
    """)
    fs = run_source(src, "tpu/kernels.py")
    assert [f.rule for f in fs] == ["jit-purity"]
    assert "global" in fs[0].message


def test_jit_clean_scan_passes():
    src = dedent("""
        import jax
        @jax.jit
        def f(x):
            import jax.numpy as jnp
            return jnp.where(x > 0, x, -x)
    """)
    assert run_source(src, "tpu/kernels.py") == []


def test_jit_alias_resolution():
    src = dedent("""
        import jax
        import time as _time
        def body(c):
            return c + _time.monotonic_ns()
        def build():
            return jax.jit(body)
    """)
    fs = run_source(src, "tpu/kernels.py")
    assert len(fs) == 1 and "time.monotonic_ns" in fs[0].message


# ---------------------------------------------------------------------------
# fixture units — fsm-determinism
# ---------------------------------------------------------------------------


def test_fsm_flags_wall_clock_in_handler():
    src = dedent("""
        import time
        class NomadFSM:
            def _apply_eval_update(self, index, payload):
                stamp = time.time_ns()
                self.state.upsert(index, payload, stamp)
        _DISPATCH = {"eval-update": NomadFSM._apply_eval_update}
    """)
    fs = run_source(src, "server/fsm.py")
    assert [f.rule for f in fs] == ["fsm-determinism"]
    assert "time.time_ns" in fs[0].message


def test_fsm_flags_transitive_self_call():
    src = dedent("""
        import random
        class NomadFSM:
            def _apply_plan(self, index, payload):
                self._helper(payload)
            def _helper(self, payload):
                return random.random()
        _DISPATCH = {"plan": NomadFSM._apply_plan}
    """)
    fs = run_source(src, "server/fsm.py")
    assert len(fs) == 1 and "random.random" in fs[0].message


def test_fsm_clean_handlers_and_unreachable_impurity():
    # impure code NOT reachable from the dispatch table is out of scope
    src = dedent("""
        import time
        class NomadFSM:
            def _apply_x(self, index, payload):
                self.state.upsert(index, payload)
            def leader_only_tick(self):
                return time.time()
        _DISPATCH = {"x": NomadFSM._apply_x}
    """)
    assert run_source(src, "server/fsm.py") == []


def test_fsm_real_module_is_deterministic():
    fsm_path = os.path.join(PKG, "server", "fsm.py")
    from nomad_tpu.analysis.fsm_determinism import FsmDeterminismChecker
    from nomad_tpu.analysis.core import parse_file

    module, err = parse_file(fsm_path, "nomad_tpu/server/fsm.py")
    assert err is None
    # the real dispatch table is found (non-trivially exercised: 30 handlers)
    checker = FsmDeterminismChecker()
    assert checker.check(module) == []


# ---------------------------------------------------------------------------
# fixture units — trace-span-discipline
# ---------------------------------------------------------------------------


def test_trace_span_flags_bare_track_call():
    # discarding the context manager: the span never opens (or worse,
    # opens in __init__-style factories and never closes)
    src = dedent("""
        from nomad_tpu.utils import phases
        def process(ev):
            phases.track("rank")
            return rank(ev)
    """)
    fs = run_source(src, "server/worker.py")
    assert [f.rule for f in fs] == ["trace-span-discipline"]
    assert "phases.track" in fs[0].message


def test_trace_span_flags_manual_enter_dance():
    # storing the manager for a manual __enter__/__exit__ pair: an
    # exception between the two leaves the span open forever
    src = dedent("""
        from ..utils import phases as _phases
        def process(ev):
            cm = _phases.track("rank")
            cm.__enter__()
            work(ev)
            cm.__exit__(None, None, None)
    """)
    fs = run_source(src, "server/worker.py")
    assert [f.rule for f in fs] == ["trace-span-discipline"]
    assert "_phases.track" in fs[0].message


def test_trace_span_flags_bare_worker_span():
    src = dedent("""
        class Worker:
            def _process(self, ev):
                self._span("invoke_scheduler", ev.id)
                self.sched.process(ev)
    """)
    fs = run_source(src, "server/worker.py")
    assert [f.rule for f in fs] == ["trace-span-discipline"]
    assert "._span" in fs[0].message


def test_trace_span_accepts_with_and_enter_context():
    src = dedent("""
        from contextlib import ExitStack
        from nomad_tpu.utils import phases
        class Worker:
            def _process(self, ev):
                with phases.track("worker_busy"):
                    with self._span("invoke_scheduler", ev.id):
                        work(ev)
                with ExitStack() as st:
                    st.enter_context(phases.track("rank"))
                    work(ev)
    """)
    assert run_source(src, "server/worker.py") == []


# ---------------------------------------------------------------------------
# fixture units — pipeline-stage-discipline
# ---------------------------------------------------------------------------


def test_pipeline_flags_raft_apply_from_pipeline_code():
    # the bug shape the rule exists to forbid: the dispatch-stage thread
    # committing around the plan queue
    src = dedent("""
        class Applier:
            def commit(self, entry_type, payload):
                return self.server.raft_apply(entry_type, payload)
    """)
    fs = run_source(src, "nomad_tpu/pipeline/applier.py")
    assert [f.rule for f in fs] == ["pipeline-stage-discipline"]
    assert "raft apply" in fs[0].message


def test_pipeline_flags_raft_dot_apply_chain():
    src = dedent("""
        class Applier:
            def commit(self, entry_type, payload):
                return self.server.raft.apply(self.server.peer, entry_type, payload)
    """)
    fs = run_source(src, "nomad_tpu/pipeline/redispatch.py")
    assert [f.rule for f in fs] == ["pipeline-stage-discipline"]
    assert "raft apply" in fs[0].message


def test_pipeline_flags_state_store_write():
    src = dedent("""
        class Applier:
            def commit(self, index, allocs):
                self.server.fsm.state.upsert_allocs(index, allocs)
    """)
    fs = run_source(src, "nomad_tpu/pipeline/applier.py")
    assert [f.rule for f in fs] == ["pipeline-stage-discipline"]
    assert "state-store write" in fs[0].message


def test_pipeline_flags_unbounded_handoff_queue():
    src = dedent("""
        import queue
        class Stage:
            def __init__(self):
                self.out = queue.Queue()
    """)
    fs = run_source(src, "nomad_tpu/pipeline/queues.py")
    assert [f.rule for f in fs] == ["pipeline-stage-discipline"]
    assert "unbounded stage queue" in fs[0].message


def test_pipeline_accepts_bounded_handoff_and_plan_queue():
    # the fixed shape: commits via plan_queue.enqueue, handoff via a
    # bounded queue; state READS (snapshot) are fine
    src = dedent("""
        import queue
        class Applier:
            def __init__(self, maxsize):
                self.out = queue.Queue(maxsize=maxsize)
            def submit(self, plan):
                snap = self.server.fsm.state.snapshot()
                pending = self.server.plan_queue.enqueue(plan)
                self.out.put(pending)
    """)
    assert run_source(src, "nomad_tpu/pipeline/applier.py") == []


def test_pipeline_rule_scoped_to_pipeline_package():
    # raft applies outside nomad_tpu/pipeline/ are the normal commit path
    src = dedent("""
        class Planner:
            def commit(self, entry_type, payload):
                return self.server.raft_apply(entry_type, payload)
    """)
    assert run_source(src, "server/plan_apply.py") == []


def test_pipeline_real_package_is_clean():
    from nomad_tpu.analysis.core import parse_file
    from nomad_tpu.analysis.pipeline_stage_discipline import (
        PipelineStageDisciplineChecker,
    )

    checker = PipelineStageDisciplineChecker()
    pkg = os.path.join(PKG, "pipeline")
    for fn in sorted(os.listdir(pkg)):
        if not fn.endswith(".py"):
            continue
        module, err = parse_file(
            os.path.join(pkg, fn), f"nomad_tpu/pipeline/{fn}")
        assert err is None
        assert checker.check(module) == [], fn


# ---------------------------------------------------------------------------
# suppression + baseline mechanics
# ---------------------------------------------------------------------------


def test_inline_suppression():
    src = dedent("""
        import jax, time
        @jax.jit
        def f(x):
            t = time.time()  # nomad-lint: disable=jit-purity
            return x
    """)
    assert run_source(src, "tpu/kernels.py") == []


def test_suppression_is_rule_scoped():
    src = dedent("""
        import jax, time
        @jax.jit
        def f(x):
            t = time.time()  # nomad-lint: disable=dtype-discipline
            return x
    """)
    assert len(run_source(src, "tpu/kernels.py")) == 1


def test_baseline_subtracts_and_reports_stale():
    f1 = Finding("jit-purity", "a.py", 3, "impure call 'time.time' in f")
    f2 = Finding("jit-purity", "a.py", 9, "impure call 'print' in g")
    base = [
        {"rule": "jit-purity", "file": "a.py",
         "message": "impure call 'time.time' in f"},
        {"rule": "dtype-discipline", "file": "b.py", "message": "gone"},
    ]
    new, stale = apply_baseline([f1, f2], base)
    assert new == [f2]
    assert stale == [{"rule": "dtype-discipline", "file": "b.py",
                      "message": "gone"}]


def test_shipped_baseline_is_valid_json_list():
    with open(BASELINE) as fh:
        data = json.load(fh)
    assert isinstance(data, list)
    for ent in data:
        assert set(ent) == {"rule", "file", "message"}


# ---------------------------------------------------------------------------
# behavioral regressions for the engine single-flight fixes
# ---------------------------------------------------------------------------


def test_release_enc_claim_clears_cache_and_wakes():
    from nomad_tpu.tpu.engine import _release_enc_claim

    ev = threading.Event()
    cache = {"key": ev}
    cell = {"ev": ev, "cache": cache, "key": "key"}
    _release_enc_claim(cell)
    assert ev.is_set() and "key" not in cache and cell == {}
    _release_enc_claim(cell)  # idempotent

    # published-entry case: the cache now holds data, not the claim — the
    # release must NOT evict it
    ev2 = threading.Event()
    cache2 = {"key": (3, "enc")}
    _release_enc_claim({"ev": ev2, "cache": cache2, "key": "key"})
    assert ev2.is_set() and cache2 == {"key": (3, "enc")}


def test_encode_eval_releases_claim_on_unexpected_exception():
    """An exception AFTER the single-flight claim must release it (pop the
    parked Event and set it) so same-key waiters don't burn their 10s
    grace period. Exercised end-to-end through encode_eval's finally."""
    from nomad_tpu.tpu.engine import TpuPlacementEngine

    engine = TpuPlacementEngine()

    class _Boom(RuntimeError):
        pass

    class _Sched:
        # encode_eval touches sched.job first inside the impl; raising
        # there models any unexpected host error mid-encode
        @property
        def job(self):
            raise _Boom("unexpected encode failure")

    cell_seen = {}
    orig = TpuPlacementEngine._encode_eval_impl

    def spy(self, sched, destructive, place, claim_cell):
        # plant a fake claim exactly as the impl's claim path would
        ev = threading.Event()
        cache = {"k": ev}
        claim_cell["ev"] = ev
        claim_cell["cache"] = cache
        claim_cell["key"] = "k"
        cell_seen["ev"] = ev
        cell_seen["cache"] = cache
        return orig(self, sched, destructive, place, claim_cell)

    TpuPlacementEngine._encode_eval_impl = spy
    try:
        with pytest.raises(_Boom):
            engine.encode_eval(_Sched(), [], [object()])
    finally:
        TpuPlacementEngine._encode_eval_impl = orig

    assert cell_seen["ev"].is_set(), "claim Event not released"
    assert cell_seen["cache"] == {}, "stuck claim left parked in enc_cache"


def test_stale_claim_timeout_wakes_waiter_cohort():
    """A timed-out waiter pops the stuck claim AND sets the dead Event so
    the remaining cohort re-reads the cache immediately instead of each
    serving its own full grace period. Modeled on the engine's waiter
    loop with a short timeout."""
    enc_cache = {}
    cache_key = "k"
    stuck = threading.Event()  # the wedged owner's claim, never set by it
    enc_cache[cache_key] = stuck

    results = []

    def waiter(grace):
        # the engine's loop shape: wait; on timeout pop + set; on wake
        # re-read the cache
        t0 = time.monotonic()
        while True:
            hit = enc_cache.get(cache_key)
            if hit is None or not isinstance(hit, threading.Event):
                results.append(("healed", time.monotonic() - t0))
                return
            if not hit.wait(timeout=grace):
                if enc_cache.get(cache_key) is hit:
                    enc_cache.pop(cache_key, None)
                hit.set()  # wake the cohort (the fix under test)
                results.append(("timeout", time.monotonic() - t0))
                return
            continue

    # one short-fuse waiter and three long-fuse cohort members
    threads = [threading.Thread(target=waiter, args=(0.2,))]
    threads += [threading.Thread(target=waiter, args=(30.0,)) for _ in range(3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=5.0)
    assert not any(t.is_alive() for t in threads), \
        "cohort members still parked on the dead claim"
    kinds = sorted(k for k, _ in results)
    assert kinds == ["healed", "healed", "healed", "timeout"]
    # the cohort healed promptly (well under its own 30s grace)
    assert all(dt < 2.0 for k, dt in results if k == "healed")


# ---------------------------------------------------------------------------
# fault-injection-discipline
# ---------------------------------------------------------------------------


def test_fault_injection_blessed_fire_hook_is_clean():
    # the ONE production shape the chaos harness allows
    src = dedent("""
        from ..chaos.injector import fire as chaos_fire

        class EvalBroker:
            def ack(self, eval_id, token):
                chaos_fire("broker_ack", eval_id=eval_id)
                return self._ack_locked(eval_id, token)
    """)
    assert run_source(src, "nomad_tpu/server/eval_broker.py") == []


def test_fault_injection_flags_adhoc_chaos_flag():
    # the bug shape rule 1 forbids: a second, registry-invisible fault path
    src = dedent("""
        CHAOS_ENABLED = False

        class Batcher:
            def run(self, enc):
                if CHAOS_ENABLED:
                    raise RuntimeError("injected")
                return self._dispatch(enc)
    """)
    fs = run_source(src, "nomad_tpu/tpu/batcher.py")
    assert fs and all(f.rule == "fault-injection-discipline" for f in fs)
    assert any("ad-hoc chaos" in f.message for f in fs)


def test_fault_injection_flags_env_gated_chaos():
    src = dedent("""
        import os

        class Planner:
            def evaluate_plan(self, snapshot, plan):
                if os.getenv("NOMAD_CHAOS_PLAN"):
                    raise RuntimeError("injected")
    """)
    fs = run_source(src, "nomad_tpu/server/plan_apply.py")
    assert [f.rule for f in fs] == ["fault-injection-discipline"]
    assert "environment-gated" in fs[0].message


def test_fault_injection_flags_production_injector_import():
    # production may import the fire hook only, never the arming surface
    src = dedent("""
        from ..chaos.injector import ChaosInjector

        class Server:
            pass
    """)
    fs = run_source(src, "nomad_tpu/server/server.py")
    assert [f.rule for f in fs] == ["fault-injection-discipline"]
    assert "only the 'fire' hook" in fs[0].message


def test_fault_injection_flags_unknown_fire_point():
    src = dedent("""
        from ..chaos.injector import fire as chaos_fire

        def apply(entry):
            chaos_fire("raft_aply")
    """)
    fs = run_source(src, "nomad_tpu/server/server.py")
    assert [f.rule for f in fs] == ["fault-injection-discipline"]
    assert "unknown injection point" in fs[0].message


def test_fault_injection_arm_with_finally_disarm_is_clean():
    src = dedent("""
        from nomad_tpu.chaos import ChaosInjector

        def test_device_fault():
            inj = ChaosInjector(seed=1)
            inj.arm("device_dispatch", prob=1.0)
            try:
                run_replay()
            finally:
                inj.disarm_all()
    """)
    assert run_source(src, "tests/test_chaos.py") == []


def test_fault_injection_flags_arm_without_finally():
    # the leak shape rule 2 forbids: an armed injector outliving its test
    src = dedent("""
        from nomad_tpu.chaos import ChaosInjector

        def test_device_fault():
            inj = ChaosInjector(seed=1)
            inj.arm("device_dispatch", prob=1.0)
            run_replay()
            inj.disarm_all()
    """)
    fs = run_source(src, "tests/test_chaos.py")
    assert [f.rule for f in fs] == ["fault-injection-discipline"]
    assert "finally" in fs[0].message


def test_fault_injection_flags_module_scope_arm():
    src = dedent("""
        from nomad_tpu.chaos import ChaosInjector

        INJ = ChaosInjector(seed=1)
        INJ.arm("heartbeat", prob=0.5)
    """)
    fs = run_source(src, "tests/test_chaos.py")
    assert [f.rule for f in fs] == ["fault-injection-discipline"]
    assert "module scope" in fs[0].message


def test_fault_injection_unblock_enqueue_point_is_known():
    # the storm-flush fire point registered with ISSUE 13: a production
    # fire on it is clean, a near-miss typo is flagged
    src = dedent("""
        from ..chaos.injector import fire as chaos_fire

        class BlockedEvals:
            def _flush_pending_locked(self):
                chaos_fire("unblock_enqueue", batch=len(self._pending))
                self.eval_broker.enqueue_all(dict(self._pending))
    """)
    assert run_source(src, "nomad_tpu/server/blocked_evals.py") == []
    typo = src.replace("unblock_enqueue", "unblock_enqueu")
    fs = run_source(typo, "nomad_tpu/server/blocked_evals.py")
    assert [f.rule for f in fs] == ["fault-injection-discipline"]
    assert "unknown injection point" in fs[0].message


def test_fault_injection_known_points_match_injector_registry():
    """The lint's _KNOWN_POINTS copy is maintained by hand (the rule
    must not import production code); this pins it to the injector's
    POINTS so a new fire point can't silently lint as unknown."""
    from nomad_tpu.analysis.fault_injection_discipline import _KNOWN_POINTS
    from nomad_tpu.chaos.injector import POINTS

    assert set(_KNOWN_POINTS) == set(POINTS)


# ---------------------------------------------------------------------------
# subprocess-discipline


def test_subprocess_flags_run_without_timeout():
    src = dedent("""
        import subprocess

        def launch():
            subprocess.run(["server", "--once"], check=True)
    """)
    fs = run_source(src, "tests/test_crash.py")
    assert [f.rule for f in fs] == ["subprocess-discipline"]
    assert "timeout" in fs[0].message


def test_subprocess_accepts_bounded_run():
    src = dedent("""
        import subprocess

        def launch():
            subprocess.run(["server", "--once"], check=True, timeout=30)
    """)
    assert run_source(src, "tests/test_crash.py") == []


def test_subprocess_flags_unbounded_proc_wait():
    src = dedent("""
        def reap(proc):
            proc.kill()
            proc.wait()
    """)
    fs = run_source(src, "nomad_tpu/chaos/crash.py")
    assert [f.rule for f in fs] == ["subprocess-discipline"]
    assert ".wait()" in fs[0].message


def test_subprocess_accepts_bounded_wait_and_lock_wait():
    # a condition-variable wait() is not a process reap: no finding
    src = dedent("""
        def reap(proc, cond):
            proc.kill()
            proc.wait(timeout=10)
            with cond:
                cond.wait()
    """)
    assert run_source(src, "nomad_tpu/chaos/crash.py") == []


def test_subprocess_flags_unowned_popen():
    # local Popen, no finally reap, not a self-attribute: leaks on the
    # first exception between spawn and reap
    src = dedent("""
        import subprocess

        def boot():
            proc = subprocess.Popen(["server"])
            wait_ready(proc)
            return proc
    """)
    fs = run_source(src, "tests/test_crash.py")
    assert [f.rule for f in fs] == ["subprocess-discipline"]
    assert "Popen" in fs[0].message


def test_subprocess_accepts_finally_reaped_popen():
    src = dedent("""
        import subprocess

        def boot():
            proc = subprocess.Popen(["server"])
            try:
                wait_ready(proc)
            finally:
                proc.kill()
                proc.wait(timeout=10)
    """)
    assert run_source(src, "tests/test_crash.py") == []


def test_subprocess_accepts_class_owned_popen():
    # the ServerProcess pattern: Popen held as a self-attribute of a
    # class that defines a reap method
    src = dedent("""
        import subprocess

        class Proc:
            def spawn(self):
                self.proc = subprocess.Popen(["server"])

            def terminate(self):
                self.proc.terminate()
                self.proc.wait(timeout=10)
    """)
    assert run_source(src, "nomad_tpu/chaos/crash.py") == []


def test_subprocess_scoped_to_harness_code():
    # production client drivers manage their own lifecycles: out of scope
    src = dedent("""
        import subprocess

        def start_task():
            p = subprocess.Popen(["workload"])
            return p
    """)
    assert run_source(src, "nomad_tpu/client/drivers/exec_driver.py") == []


# ---------------------------------------------------------------------------
# fixture units — metrics-discipline
# ---------------------------------------------------------------------------

REGISTRY_DECL = dedent("""
    FAMILIES = {
        "nomad.broker": "eval broker",
        "nomad.trace": "lifecycle spans",
    }
""")


def test_metrics_flags_fstring_name_in_loop():
    # the exact failover.py bug shape: per-key metric names minted inside
    # a loop (reverting the publish_family fix re-creates this finding)
    src = dedent("""
        from ..utils import metrics

        def publish(fields):
            for k, v in fields.items():
                metrics.set_gauge(f"nomad.chaos.failover.{k}", float(v))
    """)
    fs = run_source(src, "nomad_tpu/trace/failover.py")
    assert [f.rule for f in fs] == ["metrics-discipline"]
    assert "inside a loop" in fs[0].message
    assert "publish_family" in fs[0].message


def test_metrics_flags_non_nomad_literal():
    src = dedent("""
        from nomad_tpu.utils import metrics

        def tick():
            metrics.incr_counter("broker_enqueues")
    """)
    fs = run_source(src, "nomad_tpu/server/eval_broker.py")
    assert [f.rule for f in fs] == ["metrics-discipline"]
    assert "not a dotted" in fs[0].message


def test_metrics_flags_fully_dynamic_name():
    src = dedent("""
        from nomad_tpu.utils import metrics

        def tick(eval_id):
            metrics.add_sample("nomad.sched." + eval_id, 1.0)
    """)
    fs = run_source(src, "nomad_tpu/server/worker.py")
    assert [f.rule for f in fs] == ["metrics-discipline"]
    assert "dynamic" in fs[0].message


def test_metrics_flags_headless_fstring():
    # an f-string whose literal head isn't 'nomad.<family>.' hides the
    # family from grep even outside loops
    src = dedent("""
        from nomad_tpu.utils import metrics

        def tick(prefix):
            metrics.set_gauge(f"{prefix}.depth", 1.0)
    """)
    fs = run_source(src, "nomad_tpu/server/worker.py")
    assert [f.rule for f in fs] == ["metrics-discipline"]
    assert "literal head" in fs[0].message


def test_metrics_flags_unregistered_family_with_registry():
    # family enforcement arms only when the registry module is in the
    # collect set (full-tree runs; fixtures opt in via extra_modules)
    src = dedent("""
        from nomad_tpu.utils import metrics

        def tick():
            metrics.incr_counter("nomad.mystery.count")
    """)
    fs = run_source(
        src, "nomad_tpu/server/worker.py",
        extra_modules=[(REGISTRY_DECL, "nomad_tpu/utils/metric_names.py")])
    assert [f.rule for f in fs] == ["metrics-discipline"]
    assert "nomad.mystery" in fs[0].message and "FAMILIES" in fs[0].message


def test_metrics_accepts_literal_constant_and_bounded_fstring():
    src = dedent("""
        from nomad_tpu.utils import metrics

        STALL_GAUGE = "nomad.watchdog.stalled_s"

        def tick(eval_type):
            metrics.incr_counter("nomad.broker.enqueues")
            metrics.set_gauge(STALL_GAUGE, 2.0)
            # bounded enum suffix outside a loop: family stays greppable
            metrics.add_sample(f"nomad.trace.eval_ms.{eval_type}", 5.0)
    """)
    assert run_source(
        src, "nomad_tpu/server/worker.py",
        extra_modules=[(REGISTRY_DECL, "nomad_tpu/utils/metric_names.py")]) \
        == []


def test_metrics_accepts_publish_family_door_in_loop():
    # the blessed dynamic-name door: a literal registered prefix, dict
    # fan-out handled inside metric_names (which is itself exempt)
    src = dedent("""
        from ..utils import metric_names

        def publish(snapshots):
            for snap in snapshots:
                metric_names.publish_family("nomad.broker", snap)
    """)
    assert run_source(
        src, "nomad_tpu/server/eval_broker.py",
        extra_modules=[(REGISTRY_DECL, "nomad_tpu/utils/metric_names.py")]) \
        == []


def test_metrics_flags_dynamic_publish_family_prefix():
    src = dedent("""
        from ..utils import metric_names

        def publish(prefix, fields):
            metric_names.publish_family(prefix, fields)
    """)
    fs = run_source(src, "nomad_tpu/server/server.py")
    assert [f.rule for f in fs] == ["metrics-discipline"]
    assert "prefix" in fs[0].message


def test_metrics_exempts_sink_plumbing():
    # the sink's own fan-out and the registry door are the two modules
    # allowed to touch dynamic names
    src = dedent("""
        from . import metrics

        def publish_family(prefix, mapping):
            for key, value in mapping.items():
                metrics.set_gauge(f"{prefix}.{key}", float(value))
    """)
    assert run_source(src, "nomad_tpu/utils/metric_names.py") == []


# ---------------------------------------------------------------------------
# fixture units — lock-order
# ---------------------------------------------------------------------------


def test_lock_order_flags_lexical_inversion():
    # the planted A->B / B->A shape: two methods of one class take the
    # same pair of locks in opposite orders
    src = dedent("""
        import threading

        class A:
            def __init__(self):
                self._lk1 = threading.Lock()
                self._lk2 = threading.Lock()

            def fwd(self):
                with self._lk1:
                    with self._lk2:
                        pass

            def rev(self):
                with self._lk2:
                    with self._lk1:
                        pass
    """)
    fs = run_source(src, "server/locky.py")
    assert [f.rule for f in fs] == ["lock-order"]
    assert "potential deadlock" in fs[0].message
    assert "locky.A._lk1" in fs[0].message
    assert "locky.A._lk2" in fs[0].message


def test_lock_order_accepts_consistent_order():
    src = dedent("""
        import threading

        class A:
            def __init__(self):
                self._lk1 = threading.Lock()
                self._lk2 = threading.Lock()

            def fwd(self):
                with self._lk1:
                    with self._lk2:
                        pass

            def also_fwd(self):
                with self._lk1:
                    with self._lk2:
                        pass
    """)
    assert run_source(src, "server/locky.py") == []


def test_lock_order_walks_through_calls():
    # neither inversion is lexical: each second lock is taken in a
    # callee while the first is held in the caller — only the
    # interprocedural walk sees the cycle
    src = dedent("""
        import threading

        class B:
            def __init__(self):
                self._x = threading.Lock()
                self._y = threading.Lock()

            def top(self):
                with self._x:
                    self._grab_y()

            def _grab_y(self):
                with self._y:
                    pass

            def other(self):
                with self._y:
                    self._grab_x()

            def _grab_x(self):
                with self._x:
                    pass
    """)
    fs = run_source(src, "server/calls.py")
    assert [f.rule for f in fs] == ["lock-order"]
    assert "calls.B._x" in fs[0].message
    assert " via " in fs[0].message  # the call chain is named in the edge


def test_lock_order_through_call_consistent_is_clean():
    src = dedent("""
        import threading

        class B:
            def __init__(self):
                self._x = threading.Lock()
                self._y = threading.Lock()

            def top(self):
                with self._x:
                    self._grab_y()

            def _grab_y(self):
                with self._y:
                    pass
    """)
    assert run_source(src, "server/calls.py") == []


def test_lock_order_uses_witness_factory_literal_keys():
    # witness-created locks carry their static key as a literal: the
    # finding names the LITERAL keys, proving the static side and the
    # runtime witness share one namespace by construction
    src = dedent("""
        from nomad_tpu.utils.lock_witness import witness_lock

        class Broker:
            def __init__(self):
                self._lock = witness_lock("eval_broker.Broker._lock")
                self._q = witness_lock("eval_broker.Broker._q")

            def fwd(self):
                with self._lock:
                    with self._q:
                        pass

            def rev(self):
                with self._q:
                    with self._lock:
                        pass
    """)
    fs = run_source(src, "server/eval_broker.py")
    assert [f.rule for f in fs] == ["lock-order"]
    assert "eval_broker.Broker._lock" in fs[0].message
    assert "eval_broker.Broker._q" in fs[0].message


def test_lock_order_same_name_nesting_is_reentrant():
    # lock-class semantics: a snapshot's lock shares the live store's
    # key, so same-key nesting must not self-edge into a "cycle"
    src = dedent("""
        import threading

        class Store:
            def __init__(self):
                self._lock = threading.RLock()

            def snapshot(self):
                with self._lock:
                    other = Store()
                    with other._lock:
                        pass
    """)
    assert run_source(src, "state/state_store.py") == []


# ---------------------------------------------------------------------------
# fixture units — r06 worker-pool shapes (lock-order, trace-span-discipline)
# ---------------------------------------------------------------------------
# The parallel-lifecycle round added two concurrency-sensitive shapes:
# the batcher's demand-aware expect/cancel counter (engine threads touch
# batcher._lock while the dispatcher thread holds it around stats), and
# the worker's coalesced idle-span recording. These fixtures pin that
# the SHIPPED shapes are clean AND that the bug-shaped variants a
# refactor could reintroduce still trip the rules.


def test_worker_pool_demand_counter_shape_is_clean():
    # engine-side expect()/cancel_expected() + dispatcher-side stats
    # bump, each under the single batcher lock: no ordering edge exists
    src = dedent("""
        import threading

        class DeviceBatcher:
            def __init__(self):
                self._lock = threading.Lock()
                self._expected = 0
                self.stats = {"gathers": 0}  # guarded-by: _lock

            def expect(self, n=1):
                with self._lock:
                    self._expected += n

            def cancel_expected(self):
                with self._lock:
                    self._expected = max(0, self._expected - 1)

            def _dispatch_loop(self):
                with self._lock:
                    self.stats["gathers"] += 1
    """)
    assert run_source(src, "tpu/batcher.py") == []


def test_worker_pool_lock_order_inversion_still_trips():
    # the regression a "hold the pool lock while announcing demand"
    # refactor would create: worker pool lock -> batcher lock in one
    # path, batcher lock -> pool lock in the drain path
    src = dedent("""
        import threading

        class WorkerPool:
            def __init__(self):
                self._pool_lock = threading.Lock()
                self._batcher_lock = threading.Lock()

            def announce(self):
                with self._pool_lock:
                    with self._batcher_lock:
                        pass

            def drain(self):
                with self._batcher_lock:
                    with self._pool_lock:
                        pass
    """)
    fs = run_source(src, "server/worker.py")
    assert [f.rule for f in fs] == ["lock-order"]
    assert "potential deadlock" in fs[0].message


def test_worker_idle_span_recording_shape_is_clean():
    # the shipped worker idle pattern: pipeline_record is a plain
    # timestamped event (not a span context manager), so recording a
    # coalesced idle interval on the next successful dequeue is NOT a
    # bare-span violation — while real span entries stay `with`-guarded
    src = dedent("""
        from nomad_tpu.trace import lifecycle as _lifecycle
        from nomad_tpu.utils import phases

        class Worker:
            def _run(self):
                idle_t0 = None
                while True:
                    poll_t0 = _lifecycle.pipeline_now()
                    ev = self.dequeue()
                    if ev is None:
                        if idle_t0 is None:
                            idle_t0 = poll_t0
                        continue
                    if idle_t0 is not None:
                        _lifecycle.pipeline_record(
                            _lifecycle.IDLE_STAGE, "worker-0",
                            idle_t0, _lifecycle.pipeline_now())
                        idle_t0 = None
                    with phases.track("worker_busy"):
                        self._process(ev)
    """)
    assert run_source(src, "server/worker.py") == []


def test_worker_idle_as_bare_span_still_trips():
    # the tempting-but-wrong variant: opening a phases.track("idle")
    # manager at idle start and parking it in a local — a worker that
    # dies idle leaves the span open forever
    src = dedent("""
        from nomad_tpu.utils import phases

        class Worker:
            def _run(self):
                cm = phases.track("idle")
                cm.__enter__()
                ev = self.dequeue()
                cm.__exit__(None, None, None)
    """)
    fs = run_source(src, "server/worker.py")
    assert [f.rule for f in fs] == ["trace-span-discipline"]
    assert "phases.track" in fs[0].message


# ---------------------------------------------------------------------------
# fixture units — condition-discipline
# ---------------------------------------------------------------------------


def test_condition_flags_bare_wait():
    src = dedent("""
        import threading

        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self._cv = threading.Condition(self._lock)
                self._items = []

            def take(self):
                with self._cv:
                    self._cv.wait()
                    return self._items.pop()
    """)
    fs = run_source(src, "server/condy.py")
    assert [f.rule for f in fs] == ["condition-discipline"]
    assert "while-predicate loop" in fs[0].message


def test_condition_accepts_while_loop_and_wait_for():
    src = dedent("""
        import threading

        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self._cv = threading.Condition(self._lock)
                self._items = []

            def take(self):
                with self._cv:
                    while not self._items:
                        self._cv.wait(timeout=1.0)
                    return self._items.pop()

            def take2(self):
                with self._cv:
                    self._cv.wait_for(lambda: self._items, timeout=1.0)
                    return self._items.pop()
    """)
    assert run_source(src, "server/condy.py") == []


def test_condition_flags_unheld_notify():
    src = dedent("""
        import threading

        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self._cv = threading.Condition(self._lock)
                self._items = []

            def put(self, x):
                self._items.append(x)
                self._cv.notify()
    """)
    fs = run_source(src, "server/condy.py")
    assert [f.rule for f in fs] == ["condition-discipline"]
    assert "not provably issued with the lock held" in fs[0].message


def test_condition_accepts_provably_held_notify():
    # three proofs: lexical with, the *_locked naming convention, and
    # every-call-site-holds-it
    src = dedent("""
        import threading

        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self._cv = threading.Condition(self._lock)
                self._items = []

            def put(self, x):
                with self._cv:
                    self._items.append(x)
                    self._cv.notify()

            def _wake_locked(self):
                self._cv.notify_all()

            def _wake(self):
                self._cv.notify()

            def put2(self, x):
                with self._lock:
                    self._items.append(x)
                    self._wake()
    """)
    assert run_source(src, "server/condy.py") == []


def test_condition_ignores_non_condition_waits():
    # Event.wait / subprocess wait are not inventoried Conditions
    src = dedent("""
        import threading

        def reap(ev, proc):
            ev.wait(timeout=5)
            proc.wait(timeout=5)
    """)
    assert run_source(src, "server/condy.py") == []


# ---------------------------------------------------------------------------
# CLI satellites: --json / --rule / stale-baseline exit / --prune
# ---------------------------------------------------------------------------

CYCLE_SRC = dedent("""
    import threading

    class A:
        def __init__(self):
            self._lk1 = threading.Lock()
            self._lk2 = threading.Lock()

        def fwd(self):
            with self._lk1:
                with self._lk2:
                    pass

        def rev(self):
            with self._lk2:
                with self._lk1:
                    pass
""")


def _cli(argv):
    from nomad_tpu.analysis.__main__ import main
    return main(argv)


def test_cli_json_output_shape(tmp_path, capsys):
    mod = tmp_path / "locky.py"
    mod.write_text(CYCLE_SRC)
    rc = _cli(["--json", "--no-baseline", str(mod)])
    out = capsys.readouterr().out
    assert rc == 1
    data = json.loads(out)
    assert set(data) == {"findings", "counts", "stale_baseline",
                        "rule_wall_ms"}
    assert data["counts"] == {"lock-order": 1}
    # per-rule wall time: every reporting rule appears, plus the shared
    # interprocedural build on its own line
    assert "lock-order" in data["rule_wall_ms"]
    assert "shared-state-discipline" in data["rule_wall_ms"]
    assert "call-graph" in data["rule_wall_ms"]
    assert all(isinstance(v, (int, float)) and v >= 0
               for v in data["rule_wall_ms"].values())
    (f,) = data["findings"]
    assert set(f) == {"rule", "file", "line", "message", "rendered"}
    assert f["rule"] == "lock-order"
    assert "potential deadlock" in f["message"]
    assert f["rendered"].startswith(f["file"])
    assert data["stale_baseline"] == []


def test_cli_rule_filter(tmp_path, capsys):
    mod = tmp_path / "locky.py"
    mod.write_text(CYCLE_SRC)
    # filtered to an unrelated rule, the cycle is out of scope
    rc = _cli(["--rule", "condition-discipline", "--no-baseline", str(mod)])
    capsys.readouterr()
    assert rc == 0
    # comma-separated form includes it again
    rc = _cli(["--rule", "condition-discipline,lock-order", "--no-baseline",
               str(mod)])
    capsys.readouterr()
    assert rc == 1


def test_cli_changed_only_scopes_reporting(tmp_path, capsys):
    dirty = tmp_path / "locky.py"
    dirty.write_text(CYCLE_SRC)
    clean = tmp_path / "clean.py"
    clean.write_text("x = 1\n")

    # scoped to the clean file, the cycle in the other file is not
    # reported (though the collect pass still saw the whole tree)
    rc = _cli(["--changed-only", str(clean), "--no-baseline",
               str(tmp_path)])
    capsys.readouterr()
    assert rc == 0

    rc = _cli(["--changed-only", str(dirty), "--no-baseline",
               str(tmp_path)])
    capsys.readouterr()
    assert rc == 1

    # comma-separated form; a deleted file scopes to nothing
    rc = _cli(["--changed-only",
               f"{clean},{tmp_path / 'deleted.py'}",
               "--no-baseline", str(tmp_path)])
    capsys.readouterr()
    assert rc == 0


def test_cli_changed_only_restricts_baseline_matching(tmp_path, capsys):
    dirty = tmp_path / "locky.py"
    dirty.write_text(CYCLE_SRC)
    base = tmp_path / "baseline.json"
    # a baseline entry for a file OUTSIDE the scope must not be
    # reported stale by a scoped run
    base.write_text(json.dumps([
        {"rule": "lock-order", "file": "elsewhere.py",
         "message": "potential deadlock: out of scope"},
    ]))
    rc = _cli(["--changed-only", str(dirty), "--baseline", str(base),
               str(tmp_path)])
    capsys.readouterr()
    assert rc == 1  # the in-scope cycle still fails...
    rc = _cli(["--changed-only", str(tmp_path / "other.py"),
               "--baseline", str(base), str(tmp_path)])
    err = capsys.readouterr().err
    assert rc == 0  # ...but the out-of-scope stale entry does not
    assert "stale" not in err


def test_cli_stale_baseline_fails_and_prune_heals(tmp_path, capsys):
    mod = tmp_path / "clean.py"
    mod.write_text("x = 1\n")
    base = tmp_path / "baseline.json"
    stale_entry = {"rule": "lock-order", "file": "gone.py",
                   "message": "potential deadlock: long since fixed"}
    base.write_text(json.dumps([stale_entry]))

    # stale entries are a FAILURE, not a warning: the ratchet only tightens
    rc = _cli(["--baseline", str(base), str(mod)])
    err = capsys.readouterr().err
    assert rc == 1
    assert "stale baseline" in err

    # --prune removes exactly the stale entries and the run goes green
    rc = _cli(["--baseline", str(base), "--prune", str(mod)])
    out = capsys.readouterr().out
    assert rc == 0
    assert "pruned 1 stale entry" in out
    assert json.loads(base.read_text()) == []

    rc = _cli(["--baseline", str(base), str(mod)])
    capsys.readouterr()
    assert rc == 0


def test_cli_prune_never_adds_entries(tmp_path, capsys):
    # a tree with a NEW finding and a stale baseline: prune drops the
    # stale entry but must not launder the new finding in
    mod = tmp_path / "locky.py"
    mod.write_text(CYCLE_SRC)
    base = tmp_path / "baseline.json"
    base.write_text(json.dumps([
        {"rule": "lock-order", "file": "gone.py", "message": "fixed ages ago"},
    ]))
    rc = _cli(["--baseline", str(base), "--prune", str(mod)])
    capsys.readouterr()
    assert rc == 1  # the new finding still fails the run
    assert json.loads(base.read_text()) == []


def test_cli_write_baseline_then_green(tmp_path, capsys):
    mod = tmp_path / "locky.py"
    mod.write_text(CYCLE_SRC)
    base = tmp_path / "baseline.json"
    rc = _cli(["--baseline", str(base), "--write-baseline", str(mod)])
    capsys.readouterr()
    assert rc == 0
    rc = _cli(["--baseline", str(base), str(mod)])
    capsys.readouterr()
    assert rc == 0


# ---------------------------------------------------------------------------
# rpc-telemetry-discipline: RPC traffic must go through the instrumented
# choke points (register / RPCClient.call), or it is invisible to the
# per-method stats table and the cross-process trace
# ---------------------------------------------------------------------------


def test_rpc_telemetry_flags_raw_handler_insert():
    src = dedent("""
        def wire(rpc):
            rpc.handlers["Sneaky.call"] = lambda: 1
    """)
    fs = run_source(src, "server/extra.py")
    assert any(f.rule == "rpc-telemetry-discipline"
               and "register" in f.message for f in fs)


def test_rpc_telemetry_flags_private_frame_import_and_call():
    src = dedent("""
        from nomad_tpu.rpc.transport import _send_frame

        def leak(sock, payload):
            _send_frame(sock, payload)
    """)
    fs = run_source(src, "server/extra.py")
    assert any("side channel" in f.message for f in fs)

    src2 = dedent("""
        from nomad_tpu.rpc import transport

        def leak(sock):
            return transport._recv_frame(sock)
    """)
    fs2 = run_source(src2, "server/extra.py")
    assert any(f.rule == "rpc-telemetry-discipline"
               and "instrumented RPC path" in f.message for f in fs2)


def test_rpc_telemetry_flags_handbuilt_envelope():
    src = dedent("""
        def craft(seq):
            return {"seq": seq, "method": "Node.Register", "body": ()}
    """)
    fs = run_source(src, "server/extra.py")
    assert any(f.rule == "rpc-telemetry-discipline"
               and "TraceContext" in f.message for f in fs)


def test_rpc_telemetry_accepts_register_and_local_helpers():
    # the blessed shapes: register(), RPCClient.call, and a module's OWN
    # _read_exact helper (the websocket framer) stay clean
    src = dedent("""
        def wire(rpc, client):
            rpc.register("Status.ping", lambda: "pong")
            return client.call("Status.ping")

        def _read_exact(rfile, n):
            return rfile.read(n)

        def use(rfile):
            return _read_exact(rfile, 4)
    """)
    assert run_source(src, "server/extra.py") == []


def test_rpc_telemetry_exempts_transport_itself():
    src = dedent("""
        def handler_loop(self, method, fn):
            self.handlers[method] = fn
            return {"seq": 1, "method": method}
    """)
    assert run_source(src, "rpc/transport.py") == []
    assert run_source(src, "plugins/transport.py") == []


# ---------------------------------------------------------------------------
# blocking-read-discipline
# ---------------------------------------------------------------------------


def test_blocking_read_flags_unrouted_read_endpoint():
    # a read-shaped endpoint that answers straight from the store: no
    # QueryMeta, no min_query_index — the bug shape the funnel removed
    src = dedent("""
        def bind(rpc, server):
            rpc.register("Job.List", lambda: server.fsm.state.jobs())
            rpc.register("Eval.GetEval", lambda i: server.fsm.state.eval_by_id(i))
    """)
    fs = run_source(src, "rpc/endpoints.py")
    flagged = [f for f in fs if f.rule == "blocking-read-discipline"]
    assert len(flagged) == 2
    assert any("Job.List" in f.message for f in flagged)
    assert any("Eval.GetEval" in f.message for f in flagged)


def test_blocking_read_accepts_funnel_and_waiver():
    src = dedent("""
        def bind(rpc, server):
            def serve_read(table, run, query_opts, key=None):
                return run(server.fsm.state)

            rpc.register(
                "Job.List",
                lambda query_opts=None: serve_read(
                    "jobs", lambda s: s.jobs(), query_opts),
            )

            def get_client_allocs(node_id, min_index, timeout):
                return server.fsm.state.allocs_by_node(node_id)

            # blocking-read-waiver: pre-watch long-poll with its own
            # min_index protocol
            rpc.register("Node.GetClientAllocs", get_client_allocs)

            # write endpoints are out of scope for the funnel entirely
            rpc.register("Job.Register", server.register_job)
    """)
    assert [f for f in run_source(src, "rpc/endpoints.py")
            if f.rule == "blocking-read-discipline"] == []


def test_blocking_read_scopes_endpoint_rule_to_endpoint_modules():
    # the same unrouted register outside an endpoints.py module is some
    # other registry's business (test harnesses, plugin tables)
    src = dedent("""
        def wire(rpc, server):
            rpc.register("Job.List", lambda: server.fsm.state.jobs())
    """)
    assert [f for f in run_source(src, "server/harness.py")
            if f.rule == "blocking-read-discipline"] == []


def test_blocking_read_flags_state_writing_hub_callback():
    src = dedent("""
        def wire(hub, server):
            hub.add_callback(
                lambda tables, index: server.fsm.state.upsert_evals(index, []))
    """)
    fs = run_source(src, "server/wiring.py")
    assert any(f.rule == "blocking-read-discipline"
               and "upsert_evals" in f.message for f in fs)


def test_blocking_read_flags_lock_taking_hub_callback():
    src = dedent("""
        def wire(watch_hub, store):
            def observer(tables, index):
                with store._lock:
                    return len(store.evals)

            watch_hub.add_callback(observer)
    """)
    fs = run_source(src, "server/wiring.py")
    assert any(f.rule == "blocking-read-discipline"
               and "store._lock" in f.message for f in fs)


def test_blocking_read_accepts_observer_callback():
    # pure observation — counters, appends — is the blessed callback
    # shape; non-hub add_callback receivers are out of scope entirely
    src = dedent("""
        def wire(hub, rec, metrics, seen, server):
            hub.add_callback(lambda tables, index: seen.append(index))

            def observer(tables, index):
                metrics.incr_counter("nomad.watch.observed", len(tables))

            hub.add_callback(observer)
            rec.add_callback(lambda: server.fsm.state.upsert_evals(0, []))
    """)
    assert [f for f in run_source(src, "server/wiring.py")
            if f.rule == "blocking-read-discipline"] == []
