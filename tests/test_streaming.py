"""Streaming surfaces: server-push log follow, interactive alloc exec over
websocket (incl. the server→node bridge), and streaming agent monitor —
the HTTP realization of the reference's streaming RPC registry
(nomad/structs/streaming_rpc.go, command/agent/http.go:187,
alloc_endpoint.go execStream).
"""
import json
import threading
import time
import urllib.request

import pytest

from nomad_tpu import mock
from nomad_tpu.agent.agent import Agent, AgentConfig
from nomad_tpu.api import Client, Config


def wait_until(fn, timeout=30.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if fn():
            return
        time.sleep(0.05)
    raise AssertionError(f"timed out waiting for {msg}")


@pytest.fixture()
def agent():
    a = Agent(AgentConfig(name="stream-agent", dev_mode=True, gossip_enabled=False))
    a.start()
    yield a
    a.shutdown()


def run_job(agent, script, task_driver="raw_exec", count=1):
    job = mock.job()
    job.task_groups[0].count = count
    task = job.task_groups[0].tasks[0]
    task.driver = task_driver
    if task_driver == "raw_exec":
        task.config = {"command": "/bin/sh", "args": ["-c", script]}
    else:
        task.config = {"run_for": "60s"}
    task.resources.networks = []
    agent.server.register_job(job)

    def running():
        allocs = agent.server.fsm.state.allocs_by_job("default", job.id, True)
        return [a for a in allocs if a.client_status == "running"]

    wait_until(lambda: running(), msg="alloc running")
    return job, running()[0]


class TestLogFollowStreaming:
    def test_server_push_log_follow(self, agent):
        """A follow=true log request receives bytes written AFTER the
        stream opened — pushed by the agent, not polled."""
        job, alloc = run_job(
            agent,
            'i=0; while true; do echo "line-$i"; i=$((i+1)); sleep 0.2; done',
        )
        api = Client(Config(address=agent.http_addr))
        got = []
        stream = api.alloc_fs.logs_follow(alloc.id, "web", origin="end", offset=0)

        def consume():
            for chunk in stream:
                got.append(chunk)
                if len(b"".join(got).splitlines()) >= 3:
                    return

        t = threading.Thread(target=consume, daemon=True)
        t.start()
        t.join(timeout=20)
        joined = b"".join(got)
        assert b"line-" in joined, f"no pushed log lines: {joined[:200]!r}"
        assert len(joined.splitlines()) >= 3


class TestInteractiveExec:
    def test_exec_round_trip_local(self, agent):
        """Interactive session against `cat`: stdin comes back as stdout,
        EOF exits 0 — driven through CLI-grade SDK plumbing."""
        job, alloc = run_job(agent, "sleep 60")
        api = Client(Config(address=agent.http_addr))
        stream = api.allocations.exec_stream(alloc.id, "web", ["/bin/cat"])
        try:
            stream.send_stdin(b"hello interactive exec\n")
            chunk = stream.read_output()
            assert chunk is not None
            assert b"hello interactive exec" in chunk
            stream.send_stdin(b"second line\n")
            chunk = stream.read_output()
            assert chunk is not None and b"second line" in chunk
            stream.close_stdin()
            while stream.read_output() is not None:
                pass
            assert stream.exit_code == 0
        finally:
            stream.close()

    def test_exec_shell_session_via_cli(self, agent, monkeypatch):
        """CLI `alloc exec -i` round-trips a shell session against a live
        agent (VERDICT item 8 done-criterion)."""
        import io
        import sys as sys_mod

        from nomad_tpu.cli.main import main as cli_main

        job, alloc = run_job(agent, "sleep 60")
        stdin_buf = io.BytesIO(b"echo cli-exec-$((6*7))\nexit 3\n")
        stdout_buf = io.BytesIO()

        class FakeStd:
            def __init__(self, buf):
                self.buffer = buf

            def flush(self):
                pass

        monkeypatch.setattr(sys_mod, "stdin", FakeStd(stdin_buf))
        monkeypatch.setattr(sys_mod, "stdout", FakeStd(stdout_buf))
        code = cli_main([
            "-address", agent.http_addr,
            "alloc", "exec", "-i", "-task", "web", alloc.id[:8], "/bin/sh",
        ])
        out = stdout_buf.getvalue().decode()
        assert "cli-exec-42" in out
        assert code == 3

    def test_exec_bridged_through_server_agent(self):
        """Exec against the SERVER agent for an alloc on a separate client
        node: the websocket is bridged server→node (the streaming-RPC
        hop)."""
        server_agent = Agent(AgentConfig(
            name="exec-srv", gossip_enabled=False, client_enabled=False,
        ))
        server_agent.start()
        client_agent = Agent(AgentConfig(
            name="exec-cli", server_enabled=False, client_enabled=True,
            gossip_enabled=False,
            servers=["{}:{}".format(*server_agent.rpc.addr)],
        ))
        try:
            client_agent.start()
            wait_until(lambda: len(server_agent.server.fsm.state.nodes()) == 1,
                       msg="client node registered")
            job, alloc = run_job(server_agent, "sleep 60")
            # talk to the SERVER agent's HTTP API; alloc runs on the client
            assert client_agent.client.allocrunners.get(alloc.id) is not None
            api = Client(Config(address=server_agent.http_addr))
            stream = api.allocations.exec_stream(alloc.id, "web", ["/bin/cat"])
            try:
                stream.send_stdin(b"bridged-exec\n")
                chunk = stream.read_output()
                assert chunk is not None and b"bridged-exec" in chunk
                stream.close_stdin()
                while stream.read_output() is not None:
                    pass
                assert stream.exit_code == 0
            finally:
                stream.close()
        finally:
            client_agent.shutdown()
            server_agent.shutdown()

    def test_exec_streaming_mock_driver(self, agent):
        """The mock driver's echo session exercises the plumbing without
        real processes."""
        job, alloc = run_job(agent, "", task_driver="mock")
        api = Client(Config(address=agent.http_addr))
        stream = api.allocations.exec_stream(alloc.id, "web", ["noop"])
        try:
            stream.send_stdin(b"echo-me")
            chunk = stream.read_output()
            assert chunk == b"echo-me"
            stream.close_stdin()
            while stream.read_output() is not None:
                pass
            assert stream.exit_code == 0
        finally:
            stream.close()


class TestMonitorStreaming:
    def test_monitor_server_push(self, agent):
        """/v1/agent/monitor?follow=true pushes log lines emitted AFTER
        the stream opened."""
        import logging

        url = agent.http_addr + "/v1/agent/monitor?follow=true&log_level=info"
        resp = urllib.request.urlopen(url, timeout=10)
        got = []

        def consume():
            while True:
                chunk = resp.read1(8192)
                if not chunk:
                    return
                got.append(chunk)
                if b"streaming-sentinel" in b"".join(got):
                    return

        t = threading.Thread(target=consume, daemon=True)
        t.start()
        time.sleep(0.5)
        # warning: above the root default level, so the monitor's handler
        # on the "nomad_tpu" logger definitely sees it
        logging.getLogger("nomad_tpu.test").warning(
            "streaming-sentinel emitted after stream start"
        )
        t.join(timeout=10)
        resp.close()
        assert b"streaming-sentinel" in b"".join(got)
