"""Concurrency stress: the ``-race``-slot suite (VERDICT r3 #9; reference
GNUmakefile:293 runs `go test -race`). Python has no race sanitizer, so
these tests hammer the heavily-threaded subsystems — eval broker, plan
queue/applier, device batcher, state store — from many threads and assert
the INVARIANTS races would break:

  * no eval is delivered-and-acked twice, none is lost
  * committed capacity never exceeds any node's resources, and the
    incremental usage mirror equals the ground-truth alloc sum
  * raft/store indexes only move forward
  * every batcher request gets exactly one result (or a definite error),
    bit-identical to the single-eval oracle
"""
import random
import threading
import time

import numpy as np
import pytest

from nomad_tpu import mock
from nomad_tpu.server.eval_broker import EvalBroker
from nomad_tpu.structs.structs import (
    EVAL_STATUS_PENDING,
    Evaluation,
    generate_uuid,
)


def spin_until(fn, timeout=30.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if fn():
            return
        time.sleep(0.01)
    raise AssertionError(f"timed out: {msg}")


class TestBrokerStress:
    def test_no_lost_no_double_ack(self):
        """16 producers x 8 consumers with random nack/requeue noise:
        every eval ends acked EXACTLY once; none vanish."""
        broker = EvalBroker(nack_timeout=5.0, delivery_limit=1000,
                            initial_nack_delay=0.01,
                            subsequent_nack_delay=0.02)
        broker.set_enabled(True)
        n_per_producer = 50
        n_producers = 16
        total = n_per_producer * n_producers
        acked = {}
        acked_lock = threading.Lock()
        stop = threading.Event()
        errors = []

        def produce(pi):
            try:
                for k in range(n_per_producer):
                    ev = Evaluation(
                        job_id=f"stress-{pi}-{k}", type="service",
                        status=EVAL_STATUS_PENDING, priority=random.randint(1, 99),
                    )
                    broker.enqueue(ev)
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        def consume():
            rng = random.Random()
            while not stop.is_set():
                try:
                    ev, token = broker.dequeue(["service"], timeout=0.2)
                except Exception as e:  # noqa: BLE001
                    errors.append(e)
                    return
                if ev is None:
                    continue
                if rng.random() < 0.2:
                    try:
                        broker.nack(ev.id, token)  # redelivery path
                    except Exception as e:  # noqa: BLE001
                        errors.append(e)
                    continue
                try:
                    broker.ack(ev.id, token)
                except Exception as e:  # noqa: BLE001
                    errors.append(e)
                    continue
                with acked_lock:
                    acked[ev.id] = acked.get(ev.id, 0) + 1

        consumers = [threading.Thread(target=consume, daemon=True)
                     for _ in range(8)]
        for t in consumers:
            t.start()
        producers = [threading.Thread(target=produce, args=(pi,), daemon=True)
                     for pi in range(n_producers)]
        for t in producers:
            t.start()
        for t in producers:
            t.join()

        spin_until(lambda: len(acked) == total, msg=f"{total} evals acked")
        stop.set()
        for t in consumers:
            t.join(timeout=5)
        assert not errors, errors[:3]
        doubles = {k: v for k, v in acked.items() if v != 1}
        assert not doubles, f"double-acked: {list(doubles)[:5]}"
        stats = broker.stats()
        assert stats["total_ready"] == 0
        assert stats["total_unacked"] == 0

    def test_enable_disable_churn_never_wedges(self):
        """Leadership flaps (enable/disable) racing enqueues must neither
        deadlock nor strand evals when finally enabled."""
        broker = EvalBroker(nack_timeout=5.0)
        broker.set_enabled(True)
        stop = threading.Event()
        errors = []

        def flap():
            while not stop.is_set():
                broker.set_enabled(False)
                time.sleep(0.002)
                broker.set_enabled(True)
                time.sleep(0.002)

        enqueued = []

        def enqueue():
            for k in range(200):
                try:
                    ev = Evaluation(job_id=f"flap-{k}", type="batch")
                    broker.enqueue(ev)
                    enqueued.append(ev)
                except Exception as e:  # noqa: BLE001
                    errors.append(e)

        f = threading.Thread(target=flap, daemon=True)
        f.start()
        eq = threading.Thread(target=enqueue, daemon=True)
        eq.start()
        eq.join(timeout=20)
        stop.set()
        f.join(timeout=5)
        assert not errors
        # Re-enqueue after the final enable (a disable flush legitimately
        # drops in-memory state — the reference restores from raft on
        # re-election, which the server does via restore_evals); then
        # EVERY eval must be deliverable: none wedged, none stranded.
        broker.set_enabled(True)
        for ev in enqueued:
            broker.enqueue(ev)
        seen = set()
        deadline = time.monotonic() + 20
        while len(seen) < len(enqueued) and time.monotonic() < deadline:
            got, token = broker.dequeue(["batch"], timeout=0.5)
            if got is None:
                continue
            broker.ack(got.id, token)
            seen.add(got.id)
        assert len(seen) == len(enqueued), (
            f"stranded {len(enqueued) - len(seen)} evals after churn"
        )


class TestPlanApplierStress:
    def test_concurrent_dense_plans_never_overcommit(self):
        """24 submitter threads flooding the plan queue with dense plans
        over a small overcommitted fleet: per-node committed usage must
        NEVER exceed capacity, the usage mirror must equal the alloc
        ground truth, and indexes must be monotone."""
        from nomad_tpu.server.fsm import NODE_REGISTER
        from nomad_tpu.server.server import Server, ServerConfig
        from nomad_tpu.structs.structs import (
            AllocatedResources,
            AllocatedSharedResources,
            AllocatedTaskResources,
            DenseTGPlacements,
            Plan,
            generate_uuids,
        )

        server = Server(ServerConfig(num_schedulers=0, device_batch=0,
                                     heartbeat_min_ttl=3600,
                                     heartbeat_max_ttl=7200))
        server.start()
        try:
            node_ids = []
            for i in range(16):
                n = mock.node()
                n.name = f"stress-{i}"
                n.node_resources.cpu_shares = 1000
                n.node_resources.memory_mb = 1024
                n.compute_class()
                server.raft_apply(NODE_REGISTER, n)
                node_ids.append(n.id)

            proto = AllocatedResources(
                tasks={"t": AllocatedTaskResources(cpu_shares=100, memory_mb=100)},
                shared=AllocatedSharedResources(disk_mb=10),
            )
            results = []
            res_lock = threading.Lock()
            indexes = []

            def submit(si):
                rng = random.Random(si)
                for k in range(12):
                    per = rng.randint(1, 6)
                    chosen = [rng.randrange(len(node_ids)) for _ in range(per)]
                    block = DenseTGPlacements(
                        namespace="default", job_id=f"sj-{si}",
                        task_group="t", eval_id=f"se-{si}-{k}",
                        resources_proto=proto,
                        ask_vec=(100.0, 100.0, 10.0, 0.0),
                        ids=generate_uuids(per),
                        names=[f"sj-{si}.t[{j}]" for j in range(per)],
                        node_ids=[node_ids[c] for c in chosen],
                        node_names=[f"stress-{c}" for c in chosen],
                        scores=[1.0] * per, nodes_evaluated=[1] * per,
                    )
                    plan = Plan(eval_id=block.eval_id,
                                dense_placements=[block])
                    pending = server.plan_queue.enqueue(plan)
                    r = pending.future.result(timeout=60)
                    with res_lock:
                        results.append(r)
                        indexes.append(r.alloc_index)

            threads = [threading.Thread(target=submit, args=(si,), daemon=True)
                       for si in range(24)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=120)
            assert all(not t.is_alive() for t in threads), "submitters wedged"

            state = server.fsm.state
            from nomad_tpu.structs.funcs import alloc_usage_vec

            # ground truth vs mirror, and capacity ceiling per node
            per_node = {}
            for a in state.allocs():
                if a.terminal_status():
                    continue
                u = alloc_usage_vec(a)
                row = per_node.setdefault(a.node_id, [0.0] * 4)
                for d in range(4):
                    row[d] += u[d]
            for nid, row in per_node.items():
                mrow = state._node_usage.get(nid, (0.0,) * 4)
                assert tuple(row) == tuple(mrow), f"mirror drift on {nid[:8]}"
                node = state.node_by_id(nid)
                assert row[0] <= node.node_resources.cpu_shares + 1e-9, (
                    f"cpu overcommit on {nid[:8]}: {row[0]}"
                )
                assert row[1] <= node.node_resources.memory_mb + 1e-9, (
                    f"mem overcommit on {nid[:8]}: {row[1]}"
                )
            committed = sum(
                len(b.ids) for r in results for b in r.dense_placements
            )
            assert committed == state.count_allocs_desired_run()
            # committed plans carry positive indexes; fully-rejected plans
            # MUST carry a refresh index or their workers re-plan blind
            # against the same stale snapshot forever
            for r in results:
                if r.dense_placements:
                    assert r.alloc_index > 0
                else:
                    assert r.refresh_index > 0, "rejected plan without refresh"
            assert state.latest_index >= max(
                r.alloc_index for r in results if r.dense_placements
            )
        finally:
            server.stop()


class TestBatcherStress:
    def test_random_shapes_random_timing_all_answered(self):
        """48 submissions of random shapes from 12 threads with jittered
        arrival: every request gets exactly one result, each bit-equal to
        its single-eval oracle (sampled)."""
        from nomad_tpu.tpu.batcher import DeviceBatcher
        from nomad_tpu.tpu.engine import TpuPlacementEngine

        from tests.test_device_batcher import synthetic_enc

        engine = TpuPlacementEngine.shared()
        rng = random.Random(0)
        shapes = [(rng.choice([8, 16, 24]), rng.choice([1, 2]),
                   rng.choice([2, 4, 6]), rng.choice([0, 1]))
                  for _ in range(48)]
        encs = [synthetic_enc(n, g, p, n_spreads=s, seed=i)
                for i, (n, g, p, s) in enumerate(shapes)]
        oracle_idx = rng.sample(range(len(encs)), 6)
        oracle = {i: engine.run_scan_single(encs[i]) for i in oracle_idx}

        batcher = DeviceBatcher(max_batch=8, window_ms=10.0)
        results = [None] * len(encs)
        errors = []

        def submit(i):
            time.sleep(random.random() * 0.05)
            try:
                results[i] = batcher.run(encs[i])
            except BaseException as e:  # noqa: BLE001
                errors.append((i, e))

        threads = [threading.Thread(target=submit, args=(i,), daemon=True)
                   for i in range(len(encs))]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=180)
        batcher.stop()
        assert not errors, errors[:3]
        assert all(r is not None for r in results)
        for i, want in oracle.items():
            for k in range(4):
                np.testing.assert_array_equal(
                    np.asarray(results[i][k]), np.asarray(want[k]),
                    err_msg=f"eval {i} diverged under stress batching",
                )


class TestStateStoreStress:
    def test_snapshots_internally_consistent_under_writers(self):
        """4 writer threads churning allocs while 4 readers snapshot:
        every snapshot's usage mirror must equal the alloc sum VISIBLE IN
        THAT SNAPSHOT (copy-on-write isolation), and latest_index must
        never move backwards within a reader."""
        from nomad_tpu.state import StateStore
        from nomad_tpu.structs.funcs import alloc_usage_vec
        from nomad_tpu.structs.structs import (
            ALLOC_CLIENT_COMPLETE,
            Allocation,
            AllocatedResources,
            AllocatedSharedResources,
            AllocatedTaskResources,
        )

        store = StateStore()
        node_ids = [generate_uuid() for _ in range(8)]
        idx_lock = threading.Lock()
        idx = [0]

        def next_index():
            with idx_lock:
                idx[0] += 1
                return idx[0]

        stop = threading.Event()
        errors = []

        def writer(wi):
            rng = random.Random(wi)
            mine = []
            try:
                while not stop.is_set():
                    if mine and rng.random() < 0.4:
                        victim = mine.pop(rng.randrange(len(mine)))
                        upd = victim.copy_skip_job()
                        upd.client_status = ALLOC_CLIENT_COMPLETE
                        store.upsert_allocs(next_index(), [upd])
                    else:
                        a = Allocation(
                            job_id=f"w{wi}", task_group="t",
                            node_id=rng.choice(node_ids),
                            allocated_resources=AllocatedResources(
                                tasks={"t": AllocatedTaskResources(
                                    cpu_shares=10, memory_mb=10)},
                                shared=AllocatedSharedResources(disk_mb=1),
                            ),
                        )
                        store.upsert_allocs(next_index(), [a])
                        mine.append(a)
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        def reader():
            last = 0
            try:
                for _ in range(40):
                    snap = store.snapshot()
                    assert snap.latest_index >= last
                    last = snap.latest_index
                    per_node = {}
                    for a in snap.allocs():
                        if a.terminal_status():
                            continue
                        u = alloc_usage_vec(a)
                        row = per_node.setdefault(a.node_id, [0.0] * 4)
                        for d in range(4):
                            row[d] += u[d]
                    for nid, row in per_node.items():
                        mrow = snap._node_usage.get(nid, (0.0,) * 4)
                        assert tuple(row) == tuple(mrow), "snapshot mirror drift"
                    for nid, mrow in snap._node_usage.items():
                        if nid not in per_node:
                            assert max(mrow) <= 1e-9, "mirror ghost usage"
                    time.sleep(0.005)
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        writers = [threading.Thread(target=writer, args=(wi,), daemon=True)
                   for wi in range(4)]
        readers = [threading.Thread(target=reader, daemon=True)
                   for _ in range(4)]
        for t in writers + readers:
            t.start()
        for t in readers:
            t.join(timeout=60)
        stop.set()
        for t in writers:
            t.join(timeout=10)
        assert not errors, errors[:3]


class TestPhaseCoverage:
    def test_tracked_phases_cover_worker_busy(self):
        """ISSUE 4 acceptance: at stress scale, the fine phases must
        explain >= 90% of measured worker busy wall time — the self-check
        against round 5's blindness, where the host iterator stack burned
        wall no phase accounted for (coverage ~0.17)."""
        from nomad_tpu.server.fsm import NODE_REGISTER
        from nomad_tpu.server.server import Server, ServerConfig
        from nomad_tpu.utils import phases

        server = Server(ServerConfig(
            num_schedulers=4, device_batch=0,
            heartbeat_min_ttl=3600, heartbeat_max_ttl=7200,
        ))
        server.start()
        try:
            for i in range(32):
                n = mock.node()
                n.name = f"cov-{i}"
                n.compute_class()
                server.raft_apply(NODE_REGISTER, n)

            jobs = []
            for i in range(12):
                j = mock.job()
                j.id = f"cov-{i}"
                j.task_groups[0].count = 20
                j.task_groups[0].tasks[0].resources.cpu = 20
                j.task_groups[0].tasks[0].resources.memory_mb = 32
                jobs.append(j)
            expected = sum(tg.count for j in jobs for tg in j.task_groups)

            phases.enable()
            t0 = phases.now()
            for j in jobs:
                server.register_job(j)
            spin_until(
                lambda: server.fsm.state.count_allocs_desired_run() >= expected,
                timeout=120, msg=f"{expected} placements",
            )
            t1 = phases.now()
            cov = phases.coverage(t0, t1)
            phases.disable()

            assert cov["worker_busy"] > 0, cov
            assert cov["coverage"] >= 0.9, (
                f"fine phases explain only {cov['coverage']:.1%} of worker "
                f"busy wall time: {cov}"
            )
        finally:
            server.stop()


class TestEvalLivenessStress:
    """ISSUE 5 satellite: while the cluster HAS capacity, no eval may sit
    unacked longer than N x the broker's nack timeout — the starvation
    shape where an eval is stuck behind a wedged worker or a batcher that
    never flushes, while nodes idle. The bound is observed through the
    production surface (``nomad.trace.slowest_inflight_ms``, published by
    lifecycle.publish_gauges on the server's stats sweep), not a test-only
    probe: if the gauge can't see the starvation, operators can't either."""

    N_TIMEOUTS = 2  # liveness bound: no eval unacked > N x nack_timeout

    def test_no_eval_starves_while_capacity_exists(self):
        from nomad_tpu.server.fsm import NODE_REGISTER
        from nomad_tpu.server.server import Server, ServerConfig
        from nomad_tpu.trace import lifecycle
        from nomad_tpu.utils import metrics

        lifecycle.reset()
        metrics.global_sink().reset()

        server = Server(ServerConfig(
            num_schedulers=4, device_batch=0,
            heartbeat_min_ttl=3600, heartbeat_max_ttl=7200,
        ))
        # tighten the redelivery clock so the liveness bound bites at test
        # scale (timers read this at dequeue time, so pre-start is safe)
        server.eval_broker.nack_timeout = 5.0
        bound_ms = self.N_TIMEOUTS * server.eval_broker.nack_timeout * 1000.0
        server.start()
        try:
            for i in range(24):
                n = mock.node()
                n.name = f"live-{i}"
                n.compute_class()
                server.raft_apply(NODE_REGISTER, n)

            # 16 jobs x 12 small allocs: comfortably inside 24 mock
            # nodes, so "the cluster has capacity" holds for the whole
            # flood — any gauge spike past the bound is pure starvation
            jobs = []
            for i in range(16):
                j = mock.job()
                j.id = f"live-{i}"
                j.task_groups[0].count = 12
                j.task_groups[0].tasks[0].resources.cpu = 20
                j.task_groups[0].tasks[0].resources.memory_mb = 32
                jobs.append(j)
            expected = sum(tg.count for j in jobs for tg in j.task_groups)

            stop = threading.Event()
            observed = {"max_ms": 0.0, "samples": 0, "busy_samples": 0}

            def sample():
                # the operator's view: publish the sweep gauges and read
                # the slowest-in-flight age back out of the metrics sink
                while not stop.is_set():
                    lifecycle.publish_gauges()
                    g = {g_["Name"]: g_["Value"]
                         for g_ in metrics.global_sink().summary()["Gauges"]}
                    slow = g.get("nomad.trace.slowest_inflight_ms", 0.0)
                    observed["samples"] += 1
                    if g.get("nomad.trace.inflight", 0) > 0:
                        observed["busy_samples"] += 1
                    observed["max_ms"] = max(observed["max_ms"], slow)
                    time.sleep(0.05)

            sampler = threading.Thread(target=sample, daemon=True)
            sampler.start()
            for j in jobs:
                server.register_job(j)
            spin_until(
                lambda: server.fsm.state.count_allocs_desired_run() >= expected,
                timeout=120, msg=f"{expected} placements",
            )
            # drain the tail: placements landed, but acks may still be in
            # flight — the liveness claim covers them too
            spin_until(
                lambda: lifecycle.summary()["inflight"] == 0,
                timeout=60, msg="all evals acked",
            )
            stop.set()
            sampler.join(timeout=10)

            assert observed["busy_samples"] > 0, (
                "gauge sampler never saw an in-flight eval — the test "
                "observed nothing (flood too fast or gauges broken)"
            )
            assert observed["max_ms"] < bound_ms, (
                f"an eval sat unacked {observed['max_ms']:.0f}ms "
                f"(> {self.N_TIMEOUTS} x nack_timeout = {bound_ms:.0f}ms) "
                f"while the cluster had capacity"
            )
            # quiesced: the gauge returns to zero once the flood drains
            lifecycle.publish_gauges()
            g = {g_["Name"]: g_["Value"]
                 for g_ in metrics.global_sink().summary()["Gauges"]}
            assert g["nomad.trace.inflight"] == 0
            assert g["nomad.trace.slowest_inflight_ms"] == 0.0
        finally:
            server.stop()


class TestFlightRecorderOverhead:
    """ISSUE 12 gate: always-on observability must be near-free. The
    armed flight recorder at its production cadence (250ms) may spend at
    most 1% of wall time inside tick() while the server is flooded with
    evals, and the critical-path attribution over the same window must
    still clear its own coverage floor — cheap AND trustworthy."""

    def test_duty_cycle_under_one_percent_during_eval_flood(self):
        from nomad_tpu.server.fsm import NODE_REGISTER
        from nomad_tpu.server.server import Server, ServerConfig
        from nomad_tpu.trace import attribution, lifecycle

        lifecycle.reset()
        server = Server(ServerConfig(
            num_schedulers=4, device_batch=0,
            flight_interval_s=0.25,
            heartbeat_min_ttl=3600, heartbeat_max_ttl=7200,
        ))
        server.start()
        try:
            spin_until(lambda: server.flight.armed, msg="flight armed")
            for i in range(24):
                n = mock.node()
                n.name = f"fr-{i}"
                n.compute_class()
                server.raft_apply(NODE_REGISTER, n)

            jobs = []
            for i in range(12):
                j = mock.job()
                j.id = f"fr-{i}"
                j.task_groups[0].count = 16
                j.task_groups[0].tasks[0].resources.cpu = 20
                j.task_groups[0].tasks[0].resources.memory_mb = 32
                jobs.append(j)
            expected = sum(tg.count for j in jobs for tg in j.task_groups)
            for j in jobs:
                server.register_job(j)
            spin_until(
                lambda: server.fsm.state.count_allocs_desired_run() >= expected,
                timeout=120, msg=f"{expected} placements",
            )
            # make sure the gate judges LOADED ticks, not just idle ones
            spin_until(lambda: server.flight.overhead()["ticks"] >= 4,
                       timeout=30, msg="flight recorder ticks")
            ov = server.flight.overhead()
            assert ov["duty_cycle"] <= 0.01, (
                f"flight recorder burned {ov['duty_cycle']:.2%} of wall "
                f"time (tick avg {ov['tick_ms_avg']:.2f}ms over "
                f"{ov['ticks']} ticks) — observability is not free"
            )
            # the window it recorded must also be attributable: a cheap
            # recorder that loses track of the wall is no gate at all
            rep = attribution.bottleneck_report()
            assert rep["makespan_s"] > 0
            assert rep["coverage"] >= 0.9, (
                f"attribution covers only {rep['coverage']:.1%} of the "
                f"flood makespan: {rep['top']}"
            )
        finally:
            server.stop()


class TestBlockingQueryFanout:
    """VERDICT r4 ask #7: fleet-scale client fan-out — hundreds of
    simulated clients holding Node.GetClientAllocs blocking queries
    (state_store.blocking_query, the reference's
    state_store.go:188 / client.go:1873 watch path) while a C1M-shaped
    dense commit storm runs through the plan queue. Asserts bounded
    memory (dense placement blocks are shared + lazily materialized,
    never inflated per watcher) and timely diff delivery (p99 notify
    latency), and RECORDS both."""

    @staticmethod
    def _rss_mb() -> float:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return int(line.split()[1]) / 1024.0
        return 0.0

    def test_watcher_fanout_under_commit_storm(self):
        from nomad_tpu.server.fsm import NODE_REGISTER
        from nomad_tpu.server.server import Server, ServerConfig
        from nomad_tpu.structs.structs import (
            AllocatedResources,
            AllocatedSharedResources,
            AllocatedTaskResources,
            DenseTGPlacements,
            Plan,
            generate_uuids,
        )

        n_nodes = 200
        n_watchers = 1000
        n_plans = 64
        per_plan = 160

        server = Server(ServerConfig(
            num_schedulers=0, device_batch=0,
            heartbeat_min_ttl=3600, heartbeat_max_ttl=7200,
        ))
        server.start()
        state = server.fsm.state
        try:
            rng = np.random.default_rng(3)
            node_ids = []
            for i in range(n_nodes):
                n = mock.node()
                n.name = f"fan-{i}"
                n.compute_class()
                server.raft_apply(NODE_REGISTER, n)
                node_ids.append(n.id)

            # record commit timestamps: _bump runs under the store lock,
            # so a dict insert is safe and cheap
            bump_times = {}
            orig_bump = state._bump

            def bump_spy(index=None):
                idx = orig_bump(index)
                bump_times[idx] = time.monotonic()
                return idx

            state._bump = bump_spy

            base_index = state.latest_index
            stop = threading.Event()
            latencies = []
            lat_lock = threading.Lock()
            errors = []
            reached = [0] * n_watchers
            target_index = [None]  # set after the storm

            def watcher(wi):
                node_id = node_ids[wi % n_nodes]

                def run(s):
                    # the Node.GetClientAllocs read: the node's allocs,
                    # jobs attached (endpoints.py get_client_allocs)
                    return len(s.allocs_by_node(node_id))

                last = base_index
                try:
                    while not stop.is_set():
                        _n, idx = state.blocking_query(run, last, timeout=1.0)
                        if idx > last:
                            t = bump_times.get(idx)
                            if t is not None and idx > base_index:
                                with lat_lock:
                                    latencies.append(time.monotonic() - t)
                            last = idx
                        reached[wi] = last
                        tgt = target_index[0]
                        if tgt is not None and last >= tgt:
                            return
                except Exception as e:  # noqa: BLE001
                    errors.append(e)

            rss_before = self._rss_mb()
            threads = [
                threading.Thread(target=watcher, args=(i,), daemon=True)
                for i in range(n_watchers)
            ]
            for t in threads:
                t.start()

            proto = AllocatedResources(
                tasks={"web": AllocatedTaskResources(cpu_shares=15, memory_mb=30)},
                shared=AllocatedSharedResources(disk_mb=10),
            )

            def mk_plan(k):
                chosen = rng.choice(n_nodes, size=per_plan, replace=True)
                block = DenseTGPlacements(
                    namespace="default", job_id=f"fan-job-{k}",
                    task_group="web", eval_id=f"fan-eval-{k}",
                    resources_proto=proto, ask_vec=(15.0, 30.0, 10.0, 0.0),
                    ids=generate_uuids(per_plan),
                    names=[f"fan-job-{k}.web[{i}]" for i in range(per_plan)],
                    node_ids=[node_ids[j] for j in chosen],
                    node_names=[f"fan-{j}" for j in chosen],
                    scores=[1.0] * per_plan,
                    nodes_evaluated=[1] * per_plan,
                )
                return Plan(eval_id=f"fan-eval-{k}", dense_placements=[block])

            futures = [server.plan_queue.enqueue(mk_plan(k)).future
                       for k in range(n_plans)]
            for f in futures:
                f.result(timeout=120)
            target_index[0] = state.latest_index

            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                if all(r >= target_index[0] for r in reached):
                    break
                time.sleep(0.05)
            stop.set()
            for t in threads:
                t.join(timeout=30)
            rss_after = self._rss_mb()

            assert not errors, errors[:3]
            laggards = sum(1 for r in reached if r < target_index[0])
            assert laggards == 0, f"{laggards} watchers never saw the final index"
            assert latencies, "no notify latencies recorded"
            lat_sorted = sorted(latencies)
            p50 = lat_sorted[len(lat_sorted) // 2]
            p99 = lat_sorted[int(len(lat_sorted) * 0.99)]
            grow = rss_after - rss_before
            print(
                f"fanout: {n_watchers} watchers, {n_plans * per_plan} dense "
                f"placements committed; notify p50 {p50*1000:.0f}ms "
                f"p99 {p99*1000:.0f}ms; RSS {rss_before:.0f} -> "
                f"{rss_after:.0f}MB (+{grow:.0f}MB)"
            )
            # timely delivery: diffs reach every watcher well under the
            # blocking-query re-poll interval
            assert p99 < 5.0, f"p99 notify latency {p99:.2f}s"
            # bounded memory: 10K dense placements shared across 1000
            # watchers must not inflate per watcher (a per-watcher copy
            # of materialized allocs would be ~GBs)
            assert grow < 400, f"RSS grew {grow:.0f}MB under fan-out"
        finally:
            state._bump = orig_bump
            server.stop()


class TestLockWitnessStress:
    """nomad-lockdep's dynamic side under full scheduler pressure: arm
    the witness, flood a real server, and require (a) no order
    inversion among the instrumented locks and (b) every witnessed
    acquisition-order edge to be present in the static analyzer's
    whole-program graph — the run is the soundness proof for the static
    pass, and the static pass covers orders the flood didn't hit."""

    def test_witness_armed_flood_is_inversion_free_and_sound(self):
        from nomad_tpu.analysis.lock_order import build_static_graph
        from nomad_tpu.server.fsm import NODE_REGISTER
        from nomad_tpu.server.server import Server, ServerConfig
        from nomad_tpu.trace import lifecycle
        from nomad_tpu.utils import lock_witness, metrics

        lifecycle.reset()
        metrics.global_sink().reset()
        witness = lock_witness.arm()
        try:
            # constructed AFTER arming, so every factory-created lock in
            # the server tree is instrumented
            server = Server(ServerConfig(
                num_schedulers=4, device_batch=0,
                heartbeat_min_ttl=3600, heartbeat_max_ttl=7200,
            ))
            server.start()
            try:
                for i in range(12):
                    n = mock.node()
                    n.name = f"witness-{i}"
                    n.compute_class()
                    server.raft_apply(NODE_REGISTER, n)
                jobs = []
                for i in range(8):
                    j = mock.job()
                    j.id = f"witness-{i}"
                    j.task_groups[0].count = 8
                    j.task_groups[0].tasks[0].resources.cpu = 20
                    j.task_groups[0].tasks[0].resources.memory_mb = 32
                    jobs.append(j)
                expected = sum(tg.count for j in jobs for tg in j.task_groups)
                for j in jobs:
                    server.register_job(j)
                spin_until(
                    lambda: server.fsm.state.count_allocs_desired_run()
                    >= expected,
                    timeout=120, msg=f"{expected} witnessed placements",
                )
            finally:
                server.stop()

            stats = witness.stats()
            assert stats["violations"] == 0
            # the flood must actually exercise nested acquisition — a
            # zero-edge run would vacuously "prove" soundness
            assert stats["acquisitions"] > 1000, stats
            assert stats["edges"] > 0, stats
            missing = witness.cross_check(build_static_graph())
            assert not missing, (
                "runtime lock orders the static lock-order graph never "
                f"derived (static-analysis unsoundness): {missing}"
            )
        finally:
            lock_witness.disarm()

    def test_race_witness_armed_flood_is_race_free_and_sound(self):
        """nomad-race's dynamic side under the same eval flood: arm the
        Eraser lockset witness, flood a real server, and require (a) no
        empty-lockset violation on any tracked hot field and (b) every
        field RUNTIME-witnessed as cross-thread shared to be in the
        static analyzer's inferred-shared set — the soundness proof for
        shared-state-discipline's thread-root inventory."""
        from nomad_tpu.analysis.shared_state import build_static_shared
        from nomad_tpu.rpc import transport
        from nomad_tpu.server.fsm import NODE_REGISTER
        from nomad_tpu.server.server import Server, ServerConfig
        from nomad_tpu.trace import lifecycle
        from nomad_tpu.utils import lock_witness, metrics, race_witness

        metrics.global_sink().reset()
        witness = race_witness.arm()  # auto-arms the lock witness
        try:
            # module tables re-mint through the tracked factories only
            # AFTER arming — the import-time ones predate the witness
            lifecycle.reset()
            transport.reset_rpc_stats()
            server = Server(ServerConfig(
                num_schedulers=4, device_batch=0,
                heartbeat_min_ttl=3600, heartbeat_max_ttl=7200,
            ))
            server.start()
            try:
                for i in range(12):
                    n = mock.node()
                    n.name = f"race-{i}"
                    n.compute_class()
                    server.raft_apply(NODE_REGISTER, n)
                jobs = []
                for i in range(8):
                    j = mock.job()
                    j.id = f"race-{i}"
                    j.task_groups[0].count = 8
                    j.task_groups[0].tasks[0].resources.cpu = 20
                    j.task_groups[0].tasks[0].resources.memory_mb = 32
                    jobs.append(j)
                expected = sum(tg.count for j in jobs for tg in j.task_groups)
                for j in jobs:
                    server.register_job(j)
                spin_until(
                    lambda: server.fsm.state.count_allocs_desired_run()
                    >= expected,
                    timeout=120, msg=f"{expected} raced placements",
                )
            finally:
                server.stop()

            stats = witness.stats()
            assert stats["violations"] == 0, witness.field_report()
            # the flood must actually drive the tracked hot fields from
            # concurrent threads — a zero-access run proves nothing
            assert stats["accesses"] > 100, stats
            assert stats["shared_fields"] > 0, stats
            missing = witness.cross_check(build_static_shared())
            assert not missing, (
                "runtime-witnessed shared fields the static root "
                f"inventory never inferred as concurrent: {missing}"
            )
        finally:
            race_witness.disarm()
