"""Structs-layer semantics tests (mirrors reference nomad/structs/funcs_test.go)."""
import math

from nomad_tpu import mock
from nomad_tpu.structs import (
    Allocation,
    AllocatedResources,
    AllocatedSharedResources,
    AllocatedTaskResources,
    ComparableResources,
    Constraint,
    NetworkIndex,
    NetworkResource,
    Port,
    allocs_fit,
    compute_node_class,
    escaped_constraints,
    filter_terminal_allocs,
    remove_allocs,
    score_fit,
)
from nomad_tpu.structs.structs import (
    ALLOC_CLIENT_COMPLETE,
    ALLOC_CLIENT_FAILED,
    ALLOC_CLIENT_RUNNING,
    ALLOC_DESIRED_RUN,
    ALLOC_DESIRED_STOP,
)


def _alloc_with(cpu, mem, disk=0):
    return Allocation(
        allocated_resources=AllocatedResources(
            tasks={"web": AllocatedTaskResources(cpu_shares=cpu, memory_mb=mem)},
            shared=AllocatedSharedResources(disk_mb=disk),
        )
    )


def test_allocs_fit_single():
    n = mock.node()
    a = _alloc_with(1000, 1024, disk=5000)
    fit, dim, used = allocs_fit(n, [a])
    assert fit, dim
    # reserved (100/256) + alloc (1000/1024)
    assert used.flattened.cpu_shares == 1100
    assert used.flattened.memory_mb == 1280


def test_allocs_fit_overcommit_cpu():
    n = mock.node()
    a = _alloc_with(4000, 1024)  # node has 4000 total but 100 reserved
    fit, dim, _ = allocs_fit(n, [a])
    assert not fit
    assert dim == "cpu"


def test_allocs_fit_terminal_ignored():
    n = mock.node()
    live = _alloc_with(2000, 2048)
    dead = _alloc_with(4000, 8192)
    dead.desired_status = ALLOC_DESIRED_STOP
    fit, dim, used = allocs_fit(n, [live, dead])
    assert fit, dim
    assert used.flattened.cpu_shares == 2100


def test_allocs_fit_port_collision():
    n = mock.node()
    net = NetworkResource(device="eth0", ip="192.168.0.100", mbits=50,
                          reserved_ports=[Port("main", 8000)])
    mk = lambda: Allocation(
        allocated_resources=AllocatedResources(
            tasks={"web": AllocatedTaskResources(cpu_shares=100, memory_mb=100,
                                                 networks=[net.copy()])},
        )
    )
    fit, reason, _ = allocs_fit(n, [mk(), mk()])
    assert not fit
    assert reason == "reserved port collision"


def test_score_fit_empty_node():
    n = mock.node()
    n.reserved_resources = None
    util = ComparableResources()
    # Empty node: 20 - (10^1 + 10^1) = 0... wait free pct = 1 each -> 20-20=0
    assert score_fit(n, util) == 0.0


def test_score_fit_full_node():
    n = mock.node()
    n.reserved_resources = None
    util = ComparableResources(
        flattened=AllocatedTaskResources(cpu_shares=4000, memory_mb=8192)
    )
    # Fully used: 20 - (10^0 + 10^0) = 18
    assert score_fit(n, util) == 18.0


def test_score_fit_half():
    n = mock.node()
    n.reserved_resources = None
    util = ComparableResources(
        flattened=AllocatedTaskResources(cpu_shares=2000, memory_mb=4096)
    )
    expected = 20.0 - 2 * math.pow(10, 0.5)
    assert abs(score_fit(n, util) - expected) < 1e-9


def test_filter_terminal_allocs():
    a_live = _alloc_with(1, 1)
    a_live.name = "x[0]"
    t1 = _alloc_with(1, 1)
    t1.name = "x[1]"
    t1.desired_status = ALLOC_DESIRED_STOP
    t1.create_index = 5
    t2 = _alloc_with(1, 1)
    t2.name = "x[1]"
    t2.desired_status = ALLOC_DESIRED_STOP
    t2.create_index = 10
    live, terminal = filter_terminal_allocs([a_live, t1, t2])
    assert live == [a_live]
    assert terminal["x[1]"] is t2


def test_remove_allocs():
    a, b, c = _alloc_with(1, 1), _alloc_with(1, 1), _alloc_with(1, 1)
    out = remove_allocs([a, b, c], [b])
    assert [x.id for x in out] == [a.id, c.id]


def test_terminal_status():
    a = _alloc_with(1, 1)
    assert not a.terminal_status()
    a.client_status = ALLOC_CLIENT_FAILED
    assert a.terminal_status()
    a.client_status = ALLOC_CLIENT_RUNNING
    a.desired_status = ALLOC_DESIRED_STOP
    assert a.terminal_status()


def test_network_index_assign():
    n = mock.node()
    idx = NetworkIndex(deterministic=True)
    assert not idx.set_node(n)
    ask = NetworkResource(mbits=50, dynamic_ports=[Port("http"), Port("admin")])
    offer, err = idx.assign_network(ask)
    assert offer is not None, err
    assert offer.device == "eth0"
    assert len(offer.dynamic_ports) == 2
    assert offer.dynamic_ports[0].value != offer.dynamic_ports[1].value


def test_network_index_reserved_collision():
    n = mock.node()
    idx = NetworkIndex(deterministic=True)
    idx.set_node(n)  # reserves port 22 via reserved_host_ports
    ask = NetworkResource(mbits=10, reserved_ports=[Port("ssh", 22)])
    offer, err = idx.assign_network(ask)
    assert offer is None
    assert err == "reserved port collision"


def test_network_index_bandwidth():
    n = mock.node()
    idx = NetworkIndex(deterministic=True)
    idx.set_node(n)
    ask = NetworkResource(mbits=2000)  # node has 1000
    offer, err = idx.assign_network(ask)
    assert offer is None
    assert err == "bandwidth exceeded"


def test_computed_class_stable_and_distinct():
    n1 = mock.node()
    n2 = mock.node()
    # ids/names differ but class-relevant fields match
    assert compute_node_class(n1) == compute_node_class(n2)
    n2.attributes["kernel.name"] = "windows"
    assert compute_node_class(n1) != compute_node_class(n2)
    # unique-namespaced attributes are excluded
    n3 = mock.node()
    n3.attributes["unique.hostname"] = "zzz"
    assert compute_node_class(n1) == compute_node_class(n3)


def test_escaped_constraints():
    escaped = Constraint(ltarget="${node.unique.id}", rtarget="x", operand="=")
    unescaped = Constraint(ltarget="${attr.kernel.name}", rtarget="linux", operand="=")
    out = escaped_constraints([escaped, unescaped])
    assert out == [escaped]


def test_plan_append_pop():
    a = mock.alloc()
    plan = mock.eval().make_plan(a.job)
    plan.append_stopped_alloc(a, "test", "")
    assert len(plan.node_update[a.node_id]) == 1
    assert plan.node_update[a.node_id][0].desired_status == ALLOC_DESIRED_STOP
    # Original untouched
    assert a.desired_status == ALLOC_DESIRED_RUN
    plan.pop_update(a)
    assert a.node_id not in plan.node_update
    assert plan.is_noop()


def test_reschedule_next_delay_exponential():
    from nomad_tpu.structs.structs import RescheduleEvent, ReschedulePolicy, RescheduleTracker

    a = mock.alloc()
    tg = a.job.task_groups[0]
    tg.reschedule_policy = ReschedulePolicy(
        unlimited=True, delay_function="exponential", delay_ns=5, max_delay_ns=100
    )
    assert a.next_delay_ns() == 5
    a.reschedule_tracker = RescheduleTracker(events=[RescheduleEvent(delay_ns=5)])
    assert a.next_delay_ns() == 10
    a.reschedule_tracker.events.append(RescheduleEvent(delay_ns=10))
    assert a.next_delay_ns() == 20
