"""SystemScheduler device path: host-vs-device plan parity.

The system scheduler's placement loop is per-node select (one alloc per
eligible node, system_sched.go:268-286); the device path replaces it
with one dense forced-node scan (engine.compute_system_placements).
These tests run the same workload under ``binpack`` (host stack) and
``tpu_binpack`` (device) and assert identical plans, failures and
blocked evals.
"""
import copy
import random

import pytest

from nomad_tpu import mock
from nomad_tpu.scheduler.testing import Harness
from nomad_tpu.structs.structs import (
    EVAL_TRIGGER_JOB_REGISTER,
    Constraint,
    Evaluation,
    PreemptionConfig,
    SchedulerConfiguration,
)


def make_nodes(num, seed, cpus=(2000, 4000, 8000)):
    rng = random.Random(seed)
    nodes = []
    for i in range(num):
        n = mock.node()
        n.name = f"node-{i}"
        n.node_resources.cpu_shares = rng.choice(list(cpus))
        n.datacenter = rng.choice(["dc1", "dc2"])
        n.attributes["rack"] = f"r{rng.randint(0, 3)}"
        if rng.random() < 0.25:
            n.attributes["kernel.name"] = "windows"
        n.compute_class()
        nodes.append(n)
    return nodes


def sys_eval(job):
    return Evaluation(priority=job.priority, type=job.type,
                      triggered_by=EVAL_TRIGGER_JOB_REGISTER,
                      job_id=job.id, namespace=job.namespace)


def run_pair(nodes, jobs, preemption=True):
    plans = {}
    for alg in ("binpack", "tpu_binpack"):
        h = Harness()
        h.state.scheduler_set_config(
            h.next_index(),
            SchedulerConfiguration(
                scheduler_algorithm=alg,
                preemption_config=PreemptionConfig(
                    system_scheduler_enabled=preemption),
            ),
        )
        for n in nodes:
            h.state.upsert_node(h.next_index(), copy.deepcopy(n))
        for job in jobs:
            h.state.upsert_job(h.next_index(), copy.deepcopy(job))
        for job in jobs:
            h.process("system", sys_eval(job))
        plans[alg] = (h.plans, h.evals, h.create_evals)
    return plans


def plan_assignments(plans):
    # system allocs share one name per TG across nodes — key by node too
    out = set()
    for i, plan in enumerate(plans):
        for node_id, allocs in plan.node_allocation.items():
            for a in allocs:
                out.add((i, node_id, a.name))
    return out


def assert_parity(plans):
    host_plans, host_evals, host_blocked = plans["binpack"]
    tpu_plans, tpu_evals, tpu_blocked = plans["tpu_binpack"]
    assert len(host_plans) == len(tpu_plans)
    assert plan_assignments(host_plans) == plan_assignments(tpu_plans)
    assert len(host_blocked) == len(tpu_blocked)
    for he, te in zip(host_evals, tpu_evals):
        assert he.status == te.status
        assert set(he.failed_tg_allocs or {}) == set(te.failed_tg_allocs or {})


class _CounterSpy:
    def __init__(self, monkeypatch):
        from nomad_tpu.utils import metrics

        self.calls = []
        orig = metrics.incr_counter

        def spy(name, value=1.0):
            self.calls.append(name)
            orig(name, value)

        monkeypatch.setattr(metrics, "incr_counter", spy)


def test_system_engine_basic_parity(monkeypatch):
    spy = _CounterSpy(monkeypatch)
    nodes = make_nodes(12, seed=1)
    job = mock.system_job()
    plans = run_pair(nodes, [job])
    assert "nomad.tpu_engine.handled" in spy.calls, (
        "system job should take the engine path"
    )
    assert_parity(plans)
    # every eligible (linux) node got exactly one alloc
    got = plan_assignments(plans["tpu_binpack"][0])
    eligible = [n for n in nodes
                if n.attributes.get("kernel.name") != "windows"
                and n.datacenter == "dc1"]  # system_job targets dc1
    assert len(got) == len(eligible)


def test_system_engine_constraint_filtering_parity():
    # explicit constraint: only rack r1 nodes are in the job's domain;
    # filtered nodes are NOT failures (queued bookkeeping must agree)
    nodes = make_nodes(16, seed=2)
    job = mock.system_job()
    job.constraints.append(
        Constraint(ltarget="${attr.rack}", rtarget="r1", operand="=")
    )
    plans = run_pair(nodes, [job])
    assert_parity(plans)


def test_system_engine_capacity_failure_parity_no_preemption():
    # tiny nodes: the big ask fails on capacity -> failed_tg_allocs +
    # per-node blocked evals, identical on both paths
    nodes = make_nodes(6, seed=3, cpus=(600,))
    job = mock.system_job()
    job.task_groups[0].tasks[0].resources.cpu = 500
    busy = mock.system_job()
    busy.id = "busy"
    busy.task_groups[0].tasks[0].resources.cpu = 300
    plans = run_pair(nodes, [busy, job], preemption=False)
    assert_parity(plans)


def test_system_engine_preemption_hybrid_parity(monkeypatch):
    # capacity failure with preemption ENABLED: the device keeps every
    # clean placement and hands ONLY the preemption-needing nodes back
    # to the host per-node stack — plans and preemption sets must match
    # the pure-host run exactly
    spy = _CounterSpy(monkeypatch)
    nodes = make_nodes(4, seed=4, cpus=(1000,))
    for n in nodes:  # all eligible: dc1, linux
        n.datacenter = "dc1"
        n.attributes["kernel.name"] = "linux"
        n.compute_class()
    low = mock.system_job()
    low.id = "low-prio"
    low.priority = 20
    low.task_groups[0].tasks[0].resources.cpu = 700
    high = mock.system_job()
    high.id = "high-prio"
    high.priority = 80
    high.task_groups[0].tasks[0].resources.cpu = 700
    plans = run_pair(nodes, [low, high], preemption=True)
    assert "nomad.tpu_engine.handled" in spy.calls
    assert "nomad.tpu_engine.fallback" not in spy.calls, (
        "preemption must no longer abandon the device wholesale"
    )
    assert_parity(plans)
    # the high-priority job preempted: its plan carries preemptions
    tpu_plans = plans["tpu_binpack"][0]
    preempted = [
        a for plan in tpu_plans
        for entries in plan.node_preemptions.values() for a in entries
    ]
    assert preempted, "high-priority system job should preempt"


def test_system_engine_preemption_partial_hybrid(monkeypatch):
    """Mixed eval: some nodes fit cleanly (device path), some need
    preemption (host subset). The hybrid must keep device placements
    for the clean nodes and still match the pure-host plan."""
    spy = _CounterSpy(monkeypatch)
    nodes = make_nodes(8, seed=11, cpus=(2000,))
    for n in nodes:
        n.datacenter = "dc1"
        n.attributes["kernel.name"] = "linux"
        n.compute_class()
    low = mock.system_job()
    low.id = "low-half"
    low.priority = 20
    # low fills half the fleet via a rack constraint
    low.constraints.append(
        Constraint(ltarget="${attr.rack}", rtarget="r1", operand="=")
    )
    low.task_groups[0].tasks[0].resources.cpu = 1500
    high = mock.system_job()
    high.id = "high-all"
    high.priority = 80
    high.task_groups[0].tasks[0].resources.cpu = 900
    plans = run_pair(nodes, [low, high], preemption=True)
    assert "nomad.tpu_engine.handled" in spy.calls
    assert "nomad.tpu_engine.fallback" not in spy.calls
    assert_parity(plans)


def test_system_engine_destructive_update_parity():
    nodes = make_nodes(8, seed=5)
    results = {}
    for alg in ("binpack", "tpu_binpack"):
        h = Harness()
        h.state.scheduler_set_config(
            h.next_index(), SchedulerConfiguration(scheduler_algorithm=alg)
        )
        for n in nodes:
            h.state.upsert_node(h.next_index(), copy.deepcopy(n))
        job = mock.system_job()
        job.id = "sys-update"
        h.state.upsert_job(h.next_index(), copy.deepcopy(job))
        h.process("system", sys_eval(job))
        job2 = copy.deepcopy(job)
        job2.version = 1
        job2.task_groups[0].tasks[0].config = {"command": "/bin/new"}
        h.state.upsert_job(h.next_index(), copy.deepcopy(job2))
        h.process("system", sys_eval(job2))
        results[alg] = (h.plans, h.evals, h.create_evals)
    assert_parity(results)


def test_system_engine_port_occupied_is_exhaustion_not_filtering():
    """A node whose static port is held by ANOTHER job is EXHAUSTED
    (failed_tg_allocs + blocked eval, retried when the port frees), not
    constraint-filtered out of the domain — matching the host's
    rank-phase port exhaustion."""
    from nomad_tpu.structs.structs import Port

    nodes = make_nodes(3, seed=9)
    for n in nodes:
        n.datacenter = "dc1"
        n.attributes["kernel.name"] = "linux"
        n.compute_class()
    holder = mock.system_job()
    holder.id = "port-holder"
    from nomad_tpu.structs.structs import NetworkResource
    holder.task_groups[0].tasks[0].resources.networks = [
        NetworkResource(mbits=10, reserved_ports=[Port(label="svc", value=7777)])
    ]
    contender = mock.system_job()
    contender.id = "port-contender"
    contender.task_groups[0].tasks[0].resources.networks = [
        NetworkResource(mbits=10, reserved_ports=[Port(label="svc", value=7777)])
    ]
    plans = run_pair(nodes, [holder, contender], preemption=False)
    assert_parity(plans)
    # the contender failed (ports held) and left a blocked eval
    _, tpu_evals, tpu_blocked = plans["tpu_binpack"]
    failed = [e for e in tpu_evals if e.failed_tg_allocs]
    assert failed, "contender should record failed placements"
    assert tpu_blocked, "contender should leave blocked evals"


def test_system_engine_multi_tg_parity():
    nodes = make_nodes(10, seed=6)
    job = mock.system_job()
    tg2 = copy.deepcopy(job.task_groups[0])
    tg2.name = "second"
    tg2.tasks[0].resources.cpu = 250
    job.task_groups.append(tg2)
    plans = run_pair(nodes, [job])
    assert_parity(plans)
    # both TGs landed on every eligible node
    got = plan_assignments(plans["tpu_binpack"][0])
    eligible = [n for n in nodes
                if n.attributes.get("kernel.name") != "windows"
                and n.datacenter == "dc1"]
    assert len(got) == 2 * len(eligible)


def test_forced_kernel_bit_identical_to_scan(monkeypatch):
    """The scan-free forced-node kernel must return bit-identical
    (chosen, scores) to the sequential scan on the same encoded eval —
    asserted in-line on every system eval these scenarios produce."""
    from nomad_tpu.tpu.engine import TpuPlacementEngine

    orig = TpuPlacementEngine.run_forced
    checked = []

    def check(self, enc):
        got = orig(self, enc)
        ref = self.run_scan_single(enc)
        assert (got[0] == ref[0]).all(), "chosen diverged from the scan"
        assert (got[1] == ref[1]).all(), "scores diverged from the scan"
        checked.append(enc.p)
        return got

    monkeypatch.setattr(TpuPlacementEngine, "run_forced", check)

    # heterogeneous fleet, some windows/dc2 nodes filtered, capacity
    # collisions between the two jobs
    nodes = make_nodes(24, seed=7, cpus=(800, 2000, 4000))
    a = mock.system_job()
    a.id = "sys-a"
    a.task_groups[0].tasks[0].resources.cpu = 600
    b = mock.system_job()
    b.id = "sys-b"
    b.priority = a.priority  # same priority: no preemption, pure capacity
    b.task_groups[0].tasks[0].resources.cpu = 1500
    plans = run_pair(nodes, [a, b], preemption=False)
    assert checked, "forced kernel should have been exercised"
    assert_parity(plans)
