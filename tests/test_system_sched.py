"""System scheduler tests, mirroring reference scheduler/system_sched_test.go
core cases beyond the two in test_generic_sched: new-node fill-in, node
deregistration/drain/down stops, job updates (in-place vs destructive),
job deregistration, terminal-alloc handling and annotations.
"""
import copy

from nomad_tpu import mock
from nomad_tpu.scheduler.testing import Harness
from nomad_tpu.structs.structs import (
    ALLOC_CLIENT_RUNNING,
    ALLOC_DESIRED_RUN,
    ALLOC_DESIRED_STOP,
    EVAL_TRIGGER_JOB_REGISTER,
    EVAL_TRIGGER_NODE_UPDATE,
    Evaluation,
    SchedulerConfiguration,
)


def harness(alg="binpack"):
    h = Harness()
    h.state.scheduler_set_config(
        h.next_index(), SchedulerConfiguration(scheduler_algorithm=alg)
    )
    return h


def add_nodes(h, n, seed=0):
    nodes = []
    for i in range(n):
        node = mock.node()
        node.name = f"sys-{i}"
        node.compute_class()
        h.state.upsert_node(h.next_index(), node)
        nodes.append(node)
    return nodes


def sys_eval(job, trigger=EVAL_TRIGGER_JOB_REGISTER, node_id=""):
    return Evaluation(
        priority=job.priority, type=job.type, triggered_by=trigger,
        job_id=job.id, namespace=job.namespace, node_id=node_id,
    )


def place_system_job(h, job):
    h.state.upsert_job(h.next_index(), job)
    h.process("system", sys_eval(job))
    plan = h.plans[-1]
    allocs = [a for allocs in plan.node_allocation.values() for a in allocs]
    # feed the plan back as running state
    for a in allocs:
        a.client_status = ALLOC_CLIENT_RUNNING
    h.state.upsert_allocs(h.next_index(), allocs)
    return allocs


def test_new_node_gets_filled_in():
    """A node added after the job exists receives its system alloc on the
    node-update eval (system_sched_test.go TestSystemSched_NewNode)."""
    h = harness()
    nodes = add_nodes(h, 3)
    job = mock.system_job()
    place_system_job(h, job)
    assert sum(len(v) for v in h.plans[-1].node_allocation.values()) == 3

    late = mock.node()
    late.name = "late-node"
    late.compute_class()
    h.state.upsert_node(h.next_index(), late)
    h.process("system", sys_eval(job, EVAL_TRIGGER_NODE_UPDATE, late.id))
    plan = h.plans[-1]
    placed = [a for allocs in plan.node_allocation.values() for a in allocs]
    assert len(placed) == 1 and placed[0].node_id == late.id


def test_down_node_allocs_stopped():
    """System allocs on a down node are lost/stopped
    (TestSystemSched_NodeDown)."""
    h = harness()
    nodes = add_nodes(h, 2)
    job = mock.system_job()
    allocs = place_system_job(h, job)
    victim = nodes[0]
    downed = victim.copy()
    downed.status = "down"
    h.state.upsert_node(h.next_index(), downed)
    h.process("system", sys_eval(job, EVAL_TRIGGER_NODE_UPDATE, victim.id))
    plan = h.plans[-1]
    stopped = [a for v in plan.node_update.values() for a in v]
    assert any(a.node_id == victim.id for a in stopped)


def test_drained_node_allocs_stopped():
    """Draining stops system allocs once the drainer marks the migrate
    transition (diffSystemAllocsForNode's ShouldMigrate gate)."""
    h = harness()
    nodes = add_nodes(h, 2)
    job = mock.system_job()
    allocs = place_system_job(h, job)
    victim = nodes[1]
    drained = victim.copy()
    drained.drain = True
    h.state.upsert_node(h.next_index(), drained)
    for a in allocs:
        if a.node_id == victim.id:
            marked = a.copy_skip_job()
            marked.desired_transition.migrate = True
            h.state.upsert_allocs(h.next_index(), [marked])
    h.process("system", sys_eval(job, EVAL_TRIGGER_NODE_UPDATE, victim.id))
    plan = h.plans[-1]
    stopped = [a for v in plan.node_update.values() for a in v]
    assert any(a.node_id == victim.id for a in stopped)


def test_job_deregister_stops_everything():
    """A stopped system job stops all its allocs
    (TestSystemSched_JobDeregister)."""
    h = harness()
    add_nodes(h, 3)
    job = mock.system_job()
    place_system_job(h, job)
    stopped_job = copy.deepcopy(job)
    stopped_job.stop = True
    h.state.upsert_job(h.next_index(), stopped_job)
    h.process("system", sys_eval(job))
    plan = h.plans[-1]
    stopped = [a for v in plan.node_update.values() for a in v]
    assert len(stopped) == 3


def test_job_update_destructive():
    """A changed job destructively replaces allocs in place
    (TestSystemSched_JobModify)."""
    h = harness()
    add_nodes(h, 3)
    job = mock.system_job()
    place_system_job(h, job)
    job2 = copy.deepcopy(job)
    job2.version = 1
    job2.job_modify_index = h.next_index()
    job2.task_groups[0].tasks[0].env = {"NEW": "yes"}
    h.state.upsert_job(h.next_index(), job2)
    h.process("system", sys_eval(job2))
    plan = h.plans[-1]
    placed = [a for v in plan.node_allocation.values() for a in v]
    stopped = [a for v in plan.node_update.values() for a in v]
    assert len(placed) == 3 and len(stopped) == 3


def test_idempotent_when_in_sync():
    """Re-evaluating an unchanged, fully-placed system job is a no-op
    (TestSystemSched_JobRegister_EphemeralDiskConstraint spirit)."""
    h = harness()
    add_nodes(h, 3)
    job = mock.system_job()
    place_system_job(h, job)
    before = len(h.plans)
    h.process("system", sys_eval(job))
    # either no new plan, or an empty one
    if len(h.plans) > before:
        plan = h.plans[-1]
        assert not plan.node_allocation and not plan.node_update


def test_infeasible_nodes_annotated_not_blocking():
    """Nodes failing constraints are skipped; feasible ones still place
    (TestSystemSched_JobRegister_AddNode_Dead spirit)."""
    h = harness()
    nodes = add_nodes(h, 3)
    windows = mock.node()
    windows.attributes["kernel.name"] = "windows"
    windows.compute_class()
    h.state.upsert_node(h.next_index(), windows)
    job = mock.system_job()  # constrained to linux
    h.state.upsert_job(h.next_index(), job)
    h.process("system", sys_eval(job))
    plan = h.plans[-1]
    placed_nodes = set(plan.node_allocation)
    assert windows.id not in placed_nodes
    assert len(placed_nodes) == 3


def test_parity_system_tpu_vs_host():
    """System scheduling under tpu_binpack matches the host pipeline."""
    nodes_spec = []
    for alg in ("binpack", "tpu_binpack"):
        h = harness(alg)
        for i in range(4):
            node = mock.node()
            node.id = f"fixed-node-{i}"
            node.name = f"sys-{i}"
            node.compute_class()
            h.state.upsert_node(h.next_index(), node)
        job = mock.system_job()
        job.id = "sys-parity"
        h.state.upsert_job(h.next_index(), job)
        h.process("system", sys_eval(job))
        plan = h.plans[-1]
        nodes_spec.append(sorted(plan.node_allocation))
    assert nodes_spec[0] == nodes_spec[1]
