"""Mutual-TLS RPC tests (reference helper/tlsutil + the agent tls
stanza): encrypted transport, client-cert enforcement, and a full
TLS cluster (server agent + remote client agent) running a job.
"""
import time

import pytest

from nomad_tpu.rpc.transport import RPCClient, RPCServer, TLSConfig
from tls_helper import make_cluster_certs


def wait_until(fn, timeout=30.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if fn():
            return
        time.sleep(0.05)
    raise AssertionError(f"timed out waiting for {msg}")


@pytest.fixture(scope="module")
def certs(tmp_path_factory):
    return make_cluster_certs(str(tmp_path_factory.mktemp("tls")))


class TestTLSTransport:
    def test_mutual_tls_round_trip(self, certs):
        srv_tls = TLSConfig(*certs["server"])
        cli_tls = TLSConfig(*certs["client"])
        rpc = RPCServer(tls=srv_tls)
        rpc.register("Echo.hello", lambda x: f"hello {x}")
        rpc.start()
        try:
            cli = RPCClient(*rpc.addr, tls=cli_tls)
            assert cli.call("Echo.hello", "tls") == "hello tls"
            cli.close()
        finally:
            rpc.stop()

    def test_plaintext_client_rejected(self, certs):
        rpc = RPCServer(tls=TLSConfig(*certs["server"]))
        rpc.register("Echo.hello", lambda x: x)
        rpc.start()
        try:
            cli = RPCClient(*rpc.addr)  # no TLS
            with pytest.raises(Exception):
                cli.call("Echo.hello", "x")
            cli.close()
        finally:
            rpc.stop()

    def test_client_without_cert_rejected(self, certs, tmp_path):
        """Mutual TLS: a client presenting no certificate fails the
        handshake even with the right CA."""
        import ssl
        import socket

        rpc = RPCServer(tls=TLSConfig(*certs["server"]))
        rpc.register("Echo.hello", lambda x: x)
        rpc.start()
        try:
            ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
            ctx.load_verify_locations(certs["server"][0])
            ctx.check_hostname = False
            with pytest.raises(ssl.SSLError):
                s = socket.create_connection(rpc.addr, timeout=5)
                ws = ctx.wrap_socket(s)
                ws.send(b"x")  # handshake failure may surface on first IO
                ws.recv(1)
        finally:
            rpc.stop()


class TestServerHostnameVerification:
    def test_pinned_name_accepts_real_server(self, certs):
        srv_tls = TLSConfig(*certs["server"])
        cli_tls = TLSConfig(*certs["client"], server_name="server.global.nomad")
        assert cli_tls.pin_server_name
        rpc = RPCServer(tls=srv_tls)
        rpc.register("Echo.hello", lambda x: x)
        rpc.start()
        try:
            cli = RPCClient(*rpc.addr, tls=cli_tls)
            assert cli.call("Echo.hello", "pin") == "pin"
            cli.close()
        finally:
            rpc.stop()

    def test_client_cert_cannot_impersonate_server(self, certs):
        """A cluster-CA client cert presented by a listener must be
        rejected by callers pinning the server role name — otherwise any
        agent cert holder can MITM the RPC plane."""
        impostor = RPCServer(tls=TLSConfig(*certs["client"]))
        impostor.register("Echo.hello", lambda x: x)
        impostor.start()
        try:
            cli = RPCClient(
                *impostor.addr,
                tls=TLSConfig(*certs["client"], server_name="server.global.nomad"),
            )
            with pytest.raises(Exception):
                cli.call("Echo.hello", "x")
            cli.close()
        finally:
            impostor.stop()

    def test_opt_out_restores_ca_only_check(self, certs):
        cli_tls = TLSConfig(*certs["client"], server_name="server.global.nomad",
                            verify_server_hostname=False)
        assert not cli_tls.pin_server_name
        rpc = RPCServer(tls=TLSConfig(*certs["client"]))
        rpc.register("Echo.hello", lambda x: x)
        rpc.start()
        try:
            cli = RPCClient(*rpc.addr, tls=cli_tls)
            assert cli.call("Echo.hello", "ok") == "ok"
            cli.close()
        finally:
            rpc.stop()


class TestTLSCluster:
    def test_server_and_remote_client_over_tls(self, certs):
        """Full topology on mutual TLS: server agent + client-only agent
        dialing over the encrypted RPC plane, job placed and running."""
        from nomad_tpu import mock
        from nomad_tpu.agent.agent import Agent, AgentConfig

        ca, crt, key = certs["server"]
        server_agent = Agent(AgentConfig(
            name="tls-srv", gossip_enabled=False,
            tls_ca_file=ca, tls_cert_file=crt, tls_key_file=key,
        ))
        cca, ccrt, ckey = certs["client"]
        client_agent = Agent(AgentConfig(
            name="tls-cli", server_enabled=False, client_enabled=True,
            gossip_enabled=False,
            servers=["{}:{}".format(*server_agent.rpc.addr)],
            tls_ca_file=cca, tls_cert_file=ccrt, tls_key_file=ckey,
        ))
        try:
            server_agent.start()
            client_agent.start()
            server = server_agent.server
            wait_until(lambda: len(server.fsm.state.nodes()) == 1,
                       msg="node registered over TLS")
            job = mock.job()
            job.task_groups[0].count = 1
            task = job.task_groups[0].tasks[0]
            task.driver = "mock"
            task.config = {"run_for": "30s"}
            server.register_job(job)
            wait_until(
                lambda: any(
                    a.client_status == "running"
                    for a in server.fsm.state.allocs_by_job("default", job.id, True)
                ),
                timeout=60, msg="alloc running over TLS transport",
            )
        finally:
            client_agent.shutdown()
            server_agent.shutdown()


class TestHTTPSAgent:
    def test_https_api_with_sdk(self, certs):
        """The /v1 API over HTTPS with mTLS: SDK and endpoints work; a
        client without certs is refused."""
        from nomad_tpu.api import APIError, Client, Config
        from nomad_tpu.agent.agent import Agent, AgentConfig

        ca, crt, key = certs["server"]
        agent = Agent(AgentConfig(
            name="https", gossip_enabled=False, num_schedulers=0,
            tls_ca_file=ca, tls_cert_file=crt, tls_key_file=key,
            tls_http=True,
        ))
        try:
            agent.start()
            assert agent.http_addr.startswith("https://")
            cca, ccrt, ckey = certs["client"]
            api = Client(Config(address=agent.http_addr, ca_cert=cca,
                                client_cert=ccrt, client_key=ckey,
                                tls_skip_verify=True))
            jobs, _ = api.jobs.list()
            assert jobs == []
            info = api.agent.self()
            if isinstance(info, tuple):
                info = info[0]
            assert info["config"]["NodeName"] == "https"
            # no client cert → handshake refused
            import ssl as ssl_mod

            bare = Client(Config(address=agent.http_addr, ca_cert=cca,
                                 tls_skip_verify=True))
            # the mTLS refusal surfaces as APIError (URLError-wrapped) or
            # a raw SSLError depending on where the reset lands
            with pytest.raises((APIError, ssl_mod.SSLError, OSError)):
                bare.jobs.list()
        finally:
            agent.shutdown()
