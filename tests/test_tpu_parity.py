"""Plan parity: tpu_binpack engine vs host iterator pipeline.

The north-star requirement (BASELINE.md): identical Plan output to the stock
BinPackIterator given identical candidate order (deterministic mode).
"""
import copy
import random

import pytest

from nomad_tpu import mock
from nomad_tpu.scheduler.testing import Harness
from nomad_tpu.structs import Affinity, Constraint
from nomad_tpu.structs.structs import (
    EVAL_TRIGGER_JOB_REGISTER,
    SCHED_ALG_TPU_BINPACK,
    Evaluation,
    SchedulerConfiguration,
    Spread,
    SpreadTarget,
)


def make_nodes(num, seed):
    rng = random.Random(seed)
    nodes = []
    for i in range(num):
        n = mock.node()
        n.name = f"node-{i}"
        n.node_resources.cpu_shares = rng.choice([2000, 4000, 8000])
        n.node_resources.memory_mb = rng.choice([4096, 8192, 16384])
        n.datacenter = rng.choice(["dc1", "dc2"])
        n.attributes["rack"] = f"r{rng.randint(0, 3)}"
        if rng.random() < 0.2:
            n.attributes["kernel.name"] = "windows"
        n.compute_class()
        nodes.append(n)
    return nodes


def run_pair(nodes, jobs, evals_for):
    """Run the same workload under binpack and tpu_binpack; return plans."""
    plans = {}
    for alg in ("binpack", "tpu_binpack"):
        h = Harness()
        h.state.scheduler_set_config(
            h.next_index(), SchedulerConfiguration(scheduler_algorithm=alg)
        )
        for n in nodes:
            h.state.upsert_node(h.next_index(), copy.deepcopy(n))
        for job in jobs:
            h.state.upsert_job(h.next_index(), copy.deepcopy(job))
        for job in jobs:
            ev = Evaluation(
                priority=job.priority,
                type=job.type,
                triggered_by=EVAL_TRIGGER_JOB_REGISTER,
                job_id=job.id,
                namespace=job.namespace,
            )
            h.process(evals_for(job), ev)
        plans[alg] = (h.plans, h.evals, h.create_evals)
    return plans


def plan_assignments(plans):
    """{(eval, alloc name) -> node id} across all plans."""
    out = {}
    for i, plan in enumerate(plans):
        for node_id, allocs in plan.node_allocation.items():
            for a in allocs:
                out[(i, a.name)] = node_id
    return out


def assert_parity(plans, check_failures=True):
    host_plans, host_evals, host_blocked = plans["binpack"]
    tpu_plans, tpu_evals, tpu_blocked = plans["tpu_binpack"]
    assert len(host_plans) == len(tpu_plans)
    assert plan_assignments(host_plans) == plan_assignments(tpu_plans)
    if check_failures:
        assert len(host_blocked) == len(tpu_blocked)
        for he, te in zip(host_evals, tpu_evals):
            assert he.status == te.status
            assert set(he.failed_tg_allocs) == set(te.failed_tg_allocs)


def test_parity_basic_service():
    nodes = make_nodes(20, seed=1)
    job = mock.job()
    job.task_groups[0].count = 8
    assert_parity(run_pair(nodes, [job], lambda j: "service"))


def test_parity_multi_tg_multi_job():
    nodes = make_nodes(30, seed=2)
    jobs = []
    for ji in range(3):
        job = mock.job()
        tg0 = job.task_groups[0]
        job.task_groups = []
        for t in range(3):
            tg = copy.deepcopy(tg0)
            tg.name = f"tg{t}"
            tg.count = 4
            tg.tasks[0].resources.cpu = 300 + 100 * t
            job.task_groups.append(tg)
        jobs.append(job)
    assert_parity(run_pair(nodes, jobs, lambda j: "service"))


def test_parity_batch_power_of_two():
    nodes = make_nodes(25, seed=3)
    job = mock.batch_job()
    job.task_groups[0].count = 12
    assert_parity(run_pair(nodes, [job], lambda j: "batch"))


def test_parity_affinities():
    nodes = make_nodes(20, seed=4)
    job = mock.job()
    job.task_groups[0].count = 6
    job.affinities = [Affinity("${attr.rack}", "r1", "=", 75)]
    job.task_groups[0].affinities = [Affinity("${node.datacenter}", "dc2", "=", -30)]
    job.datacenters = ["dc1", "dc2"]
    assert_parity(run_pair(nodes, [job], lambda j: "service"))


def test_parity_spread():
    nodes = make_nodes(24, seed=5)
    job = mock.job()
    job.task_groups[0].count = 10
    job.datacenters = ["dc1", "dc2"]
    job.spreads = [
        Spread("${node.datacenter}", 100, [SpreadTarget("dc1", 70), SpreadTarget("dc2", 30)])
    ]
    assert_parity(run_pair(nodes, [job], lambda j: "service"))


def test_parity_even_spread():
    nodes = make_nodes(16, seed=6)
    job = mock.job()
    job.task_groups[0].count = 8
    job.task_groups[0].spreads = [Spread("${attr.rack}", 50, [])]
    assert_parity(run_pair(nodes, [job], lambda j: "service"))


def test_parity_distinct_hosts():
    nodes = make_nodes(15, seed=7)
    job = mock.job()
    job.task_groups[0].count = 10
    job.constraints.append(Constraint(operand="distinct_hosts"))
    assert_parity(run_pair(nodes, [job], lambda j: "service"))


def test_parity_overcommitted_cluster():
    """More asks than capacity: failures + blocked evals must match."""
    nodes = make_nodes(5, seed=8)
    for n in nodes:
        n.node_resources.cpu_shares = 1000
    job = mock.job()
    job.task_groups[0].count = 20
    job.task_groups[0].tasks[0].resources.cpu = 400
    assert_parity(run_pair(nodes, [job], lambda j: "service"))


def test_parity_scale_up_down():
    nodes = make_nodes(18, seed=9)
    job = mock.job()
    job.task_groups[0].count = 9

    for alg in ("binpack", "tpu_binpack"):
        pass  # runs inside run_pair-like flow below

    results = {}
    for alg in ("binpack", "tpu_binpack"):
        h = Harness()
        h.state.scheduler_set_config(
            h.next_index(), SchedulerConfiguration(scheduler_algorithm=alg)
        )
        for n in nodes:
            h.state.upsert_node(h.next_index(), copy.deepcopy(n))
        j = copy.deepcopy(job)
        h.state.upsert_job(h.next_index(), j)
        ev = Evaluation(priority=j.priority, type=j.type,
                        triggered_by=EVAL_TRIGGER_JOB_REGISTER,
                        job_id=j.id, namespace=j.namespace)
        h.process("service", ev)
        # scale up
        j2 = copy.deepcopy(j)
        j2.task_groups[0].count = 14
        h.state.upsert_job(h.next_index(), j2)
        ev2 = Evaluation(priority=j2.priority, type=j2.type,
                         triggered_by=EVAL_TRIGGER_JOB_REGISTER,
                         job_id=j2.id, namespace=j2.namespace)
        h.process("service", ev2)
        # destructive update
        j3 = copy.deepcopy(j2)
        j3.task_groups[0].tasks[0].config = {"command": "/bin/new"}
        h.state.upsert_job(h.next_index(), j3)
        ev3 = Evaluation(priority=j3.priority, type=j3.type,
                         triggered_by=EVAL_TRIGGER_JOB_REGISTER,
                         job_id=j3.id, namespace=j3.namespace)
        h.process("service", ev3)
        results[alg] = h.plans

    assert plan_assignments(results["binpack"]) == plan_assignments(results["tpu_binpack"])


def test_parity_fuzz():
    """Randomized configs; any divergence is a real parity bug."""
    for seed in range(10, 16):
        rng = random.Random(seed)
        nodes = make_nodes(rng.randint(5, 40), seed=seed)
        jobs = []
        for _ in range(rng.randint(1, 3)):
            job = mock.job()
            tg = job.task_groups[0]
            tg.count = rng.randint(1, 12)
            tg.tasks[0].resources.cpu = rng.choice([100, 500, 1500])
            tg.tasks[0].resources.memory_mb = rng.choice([64, 256, 1024])
            if rng.random() < 0.5:
                job.affinities = [Affinity("${attr.rack}", f"r{rng.randint(0,3)}", "=",
                                           rng.choice([-50, 50, 100]))]
            if rng.random() < 0.5:
                job.datacenters = ["dc1", "dc2"]
                job.spreads = [Spread("${node.datacenter}", 50,
                                      [SpreadTarget("dc1", rng.choice([0, 40, 60]))])]
            if rng.random() < 0.3:
                job.constraints.append(Constraint(operand="distinct_hosts"))
            jobs.append(job)
        plans = run_pair(nodes, jobs, lambda j: "service")
        host = plan_assignments(plans["binpack"][0])
        tpu = plan_assignments(plans["tpu_binpack"][0])
        assert host == tpu, f"seed {seed}: parity diverged"


class TestPreemptionParity:
    """Device-vs-host bit-equality for the preemption engine: the TPU
    scan's eviction sets (tpu/preempt.py kernels) must match the host
    Preemptor (scheduler/preemption.py) victim-for-victim — same nodes,
    same evicted allocs, same final eviction order on each preemptor's
    ``preempted_allocations``. Both paths evaluate the same exact int
    spec, so any divergence is a real engine bug, not rounding."""

    @staticmethod
    def _run_pair(nodes, victim_jobs, preemptor_jobs):
        from nomad_tpu.structs.structs import PreemptionConfig

        plans = {}
        for alg in ("binpack", "tpu_binpack"):
            h = Harness()
            h.state.scheduler_set_config(
                h.next_index(),
                SchedulerConfiguration(
                    scheduler_algorithm=alg,
                    preemption_config=PreemptionConfig(
                        system_scheduler_enabled=True,
                        service_scheduler_enabled=True,
                        batch_scheduler_enabled=True,
                    ),
                ),
            )
            for n in nodes:
                h.state.upsert_node(h.next_index(), copy.deepcopy(n))
            # phase 1 fills the cluster with low-priority victims; phase 2
            # schedules the high-priority preemptors over the full fleet
            for phase in (victim_jobs, preemptor_jobs):
                for job in phase:
                    j = copy.deepcopy(job)
                    h.state.upsert_job(h.next_index(), j)
                    ev = Evaluation(
                        priority=j.priority, type=j.type,
                        triggered_by=EVAL_TRIGGER_JOB_REGISTER,
                        job_id=j.id, namespace=j.namespace,
                    )
                    h.process(j.type, ev)
            plans[alg] = (h.plans, h.evals, h.create_evals)
        return plans

    @staticmethod
    def _preemption_view(plans):
        """UUID-free projection of each plan's preemption outcome: alloc
        ids differ between the two harness runs, so victims are keyed by
        (job_id, task_group) and preemptors by alloc NAME (both
        deterministic)."""
        out = {}
        for i, plan in enumerate(plans):
            stub_by_id = {}
            for nid, stubs in plan.node_preemptions.items():
                for s in stubs:
                    stub_by_id[s.id] = (nid, s.job_id, s.task_group)
                out[(i, "victims", nid)] = sorted(
                    (s.job_id, s.task_group) for s in stubs
                )
            for nid, allocs in plan.node_allocation.items():
                for a in allocs:
                    if a.preempted_allocations:
                        # ORDER preserved: the final second-pass eviction
                        # order must match, not just the victim set
                        out[(i, "by", a.name)] = [
                            stub_by_id.get(v) for v in a.preempted_allocations
                        ]
        return out

    def assert_preempt_parity(self, plans, require_preemptions=False):
        host_plans, host_evals, _hb = plans["binpack"]
        tpu_plans, tpu_evals, _tb = plans["tpu_binpack"]
        assert len(host_plans) == len(tpu_plans)
        assert plan_assignments(host_plans) == plan_assignments(tpu_plans)
        hv = self._preemption_view(host_plans)
        tv = self._preemption_view(tpu_plans)
        assert hv == tv, "preemption outcome diverged device vs host"
        for he, te in zip(host_evals, tpu_evals):
            assert he.status == te.status
            assert set(he.failed_tg_allocs) == set(te.failed_tg_allocs)
        if require_preemptions:
            assert any(k[1] == "victims" for k in tv), (
                "scenario was expected to exercise preemption"
            )

    @staticmethod
    def _plain_service(priority, count, cpu, mem):
        job = mock.job()
        job.priority = priority
        tg = job.task_groups[0]
        tg.count = count
        tg.tasks[0].resources.cpu = cpu
        tg.tasks[0].resources.memory_mb = mem
        # no network asks: networks force the host fallback by design
        tg.tasks[0].resources.networks = []
        return job

    @staticmethod
    def _uniform_nodes(num, cpu=2000, mem=4096):
        nodes = []
        for i in range(num):
            n = mock.node()
            n.name = f"pnode-{i}"
            n.node_resources.cpu_shares = cpu
            n.node_resources.memory_mb = mem
            n.compute_class()
            nodes.append(n)
        return nodes

    def test_service_preempts_low_priority(self, monkeypatch):
        """Saturated fleet, high-priority service job: placements ride
        the device (engine handled counter) and evict the same victims
        in the same order as the host oracle."""
        spy = _CounterSpy(monkeypatch)
        nodes = self._uniform_nodes(6)
        low = self._plain_service(20, 6, 1500, 2048)  # one per node
        high = self._plain_service(70, 3, 1000, 1024)  # needs eviction
        plans = self._run_pair(nodes, [low], [high])
        assert "nomad.tpu_engine.handled" in spy.calls
        self.assert_preempt_parity(plans, require_preemptions=True)

    def test_no_preemption_below_priority_delta(self):
        """Priority gap under PRIORITY_DELTA: neither path evicts and the
        blocked/failed bookkeeping matches."""
        nodes = self._uniform_nodes(4)
        low = self._plain_service(45, 4, 1500, 2048)
        close = self._plain_service(50, 2, 1000, 1024)  # delta 5 < 10
        plans = self._run_pair(nodes, [low], [close])
        self.assert_preempt_parity(plans)
        assert not any(
            k[1] == "victims" for k in
            self._preemption_view(plans["tpu_binpack"][0])
        )

    def test_system_job_preemption_parity(self):
        """System scheduler second pass: forced one-per-node placements
        that fail capacity re-enter the engine as a preemption pass."""
        nodes = self._uniform_nodes(5)
        low = self._plain_service(20, 5, 1500, 2048)
        high = mock.system_job()
        high.priority = 80
        high.task_groups[0].tasks[0].resources.cpu = 1000
        high.task_groups[0].tasks[0].resources.memory_mb = 512
        plans = self._run_pair(nodes, [low], [high])
        self.assert_preempt_parity(plans, require_preemptions=True)

    def test_preemption_fuzz(self):
        """Randomized saturated clusters + preemptors; any divergence in
        victims, order or placements is a real parity bug. Runnable on a
        real chip via NOMAD_TPU_TEST_PLATFORM=axon — the int spec makes
        the comparison exact there too."""
        preempting_seeds = 0
        for seed in range(40, 46):
            rng = random.Random(seed)
            num = rng.randint(3, 10)
            nodes = self._uniform_nodes(
                num, cpu=rng.choice([2000, 3000]), mem=4096)
            victims = []
            for vi in range(rng.randint(1, 2)):
                victims.append(self._plain_service(
                    rng.choice([10, 20, 30]), num,
                    rng.choice([600, 900, 1200]),
                    rng.choice([512, 1024, 2048]),
                ))
            preemptor = self._plain_service(
                rng.choice([60, 80]), rng.randint(1, num),
                rng.choice([800, 1200, 1600]),
                rng.choice([1024, 2048]),
            )
            plans = self._run_pair(nodes, victims, [preemptor])
            self.assert_preempt_parity(plans)
            if any(
                k[1] == "victims"
                for k in self._preemption_view(plans["tpu_binpack"][0])
            ):
                preempting_seeds += 1
        # the fuzz must actually exercise the eviction path, not just
        # vacuously agree on preemption-free plans
        assert preempting_seeds >= 2


class _CounterSpy:
    """Record engine path counters event-wise (the in-mem sink's interval
    retention makes before/after count comparisons flaky)."""

    def __init__(self, monkeypatch):
        from nomad_tpu.utils import metrics

        self.calls = []
        orig = metrics.incr_counter

        def spy(name, value=1.0):
            self.calls.append(name)
            orig(name, value)

        monkeypatch.setattr(metrics, "incr_counter", spy)


def test_parity_device_counts_on_engine(monkeypatch):
    """Plain count-based device asks take the DEVICE path (capacity dims +
    host-side instance assignment) with plan parity."""
    spy = _CounterSpy(monkeypatch)
    nodes = [mock.nvidia_node() for _ in range(3)]
    job = mock.job()
    job.task_groups[0].count = 4
    from nomad_tpu.structs.structs import RequestedDevice

    job.task_groups[0].tasks[0].resources.devices = [RequestedDevice(name="gpu", count=1)]
    plans = run_pair(nodes, [job], lambda j: "service")
    assert "nomad.tpu_engine.handled" in spy.calls, (
        "device-count job should take the engine path"
    )
    assert len(plan_assignments(plans["tpu_binpack"][0])) == 4
    assert plan_assignments(plans["binpack"][0]) == plan_assignments(plans["tpu_binpack"][0])
    # every placed alloc carries concrete device instances
    for plan in plans["tpu_binpack"][0]:
        for allocs in plan.node_allocation.values():
            for a in allocs:
                devs = [d for tr in a.allocated_resources.tasks.values() for d in tr.devices]
                assert devs and all(d.device_ids for d in devs)


def test_parity_device_exhaustion():
    """More GPU asks than instances: failures must match the host path."""
    nodes = [mock.nvidia_node() for _ in range(2)]  # 2 nodes x 2 instances
    job = mock.job()
    job.task_groups[0].count = 6  # asks 6 GPUs, only 4 exist
    from nomad_tpu.structs.structs import RequestedDevice

    job.task_groups[0].tasks[0].resources.devices = [RequestedDevice(name="gpu", count=1)]
    assert_parity(run_pair(nodes, [job], lambda j: "service"))


def test_parity_reserved_ports_on_engine(monkeypatch):
    """Reserved-port jobs take the device path: static port-feasibility
    mask + same-TG-per-node exclusion, identical plans to the host."""
    from nomad_tpu.structs.structs import Port

    spy = _CounterSpy(monkeypatch)
    nodes = make_nodes(8, seed=21)
    job = mock.job()
    job.task_groups[0].count = 5
    job.task_groups[0].tasks[0].resources.networks[0].reserved_ports = [
        Port(label="http", value=8080)
    ]
    plans = run_pair(nodes, [job], lambda j: "service")
    assert "nomad.tpu_engine.handled" in spy.calls, (
        "reserved-port job should take the engine path"
    )
    assert_parity(plans)
    # self-exclusion: no node hosts two instances (they'd collide on 8080)
    for plan in plans["tpu_binpack"][0]:
        for node_id, allocs in plan.node_allocation.items():
            assert len(allocs) <= 1


def test_parity_reserved_ports_competing_jobs():
    """Two jobs fighting for the same static port: the second job must
    avoid nodes the first claimed — identically on both paths."""
    from nomad_tpu.structs.structs import Port

    nodes = make_nodes(10, seed=22)
    jobs = []
    for i in range(2):
        job = mock.job()
        job.id = f"port-fight-{i}"
        job.task_groups[0].count = 4
        job.task_groups[0].tasks[0].resources.networks[0].reserved_ports = [
            Port(label="svc", value=9999)
        ]
        jobs.append(job)
    plans = run_pair(nodes, jobs, lambda j: "service")
    assert_parity(plans)
    # across BOTH jobs, port 9999 is claimed at most once per node
    node_claims = {}
    for plan in plans["tpu_binpack"][0]:
        for node_id, allocs in plan.node_allocation.items():
            node_claims[node_id] = node_claims.get(node_id, 0) + len(allocs)
    assert all(v <= 1 for v in node_claims.values())


def test_parity_reserved_ports_destructive_update():
    """Destructive update of a reserved-port job: the replacement may land
    on the SAME node because the eviction frees the port first."""
    from nomad_tpu.structs.structs import Port

    nodes = make_nodes(6, seed=23)
    results = {}
    for alg in ("binpack", "tpu_binpack"):
        h = Harness()
        h.state.scheduler_set_config(
            h.next_index(), SchedulerConfiguration(scheduler_algorithm=alg)
        )
        for n in nodes:
            h.state.upsert_node(h.next_index(), copy.deepcopy(n))
        job = mock.job()
        job.id = "port-update"
        job.task_groups[0].count = 3
        job.task_groups[0].tasks[0].resources.networks[0].reserved_ports = [
            Port(label="http", value=7070)
        ]
        h.state.upsert_job(h.next_index(), copy.deepcopy(job))
        ev = Evaluation(priority=50, type="service",
                        triggered_by=EVAL_TRIGGER_JOB_REGISTER,
                        job_id=job.id, namespace="default")
        h.process("service", ev)
        # apply the plan into state, then bump the job (destructive change)
        job2 = copy.deepcopy(job)
        job2.version = 1
        job2.job_modify_index = h.next_index()
        job2.task_groups[0].tasks[0].env = {"V": "2"}
        h.state.upsert_job(h.next_index(), copy.deepcopy(job2))
        ev2 = Evaluation(priority=50, type="service",
                         triggered_by=EVAL_TRIGGER_JOB_REGISTER,
                         job_id=job.id, namespace="default")
        h.process("service", ev2)
        results[alg] = (h.plans, h.evals, h.create_evals)
    assert_parity(results)


def test_fallback_metrics_for_unsupported(monkeypatch):
    """Unsupported shapes still fall back — and the fallback is counted."""
    spy = _CounterSpy(monkeypatch)
    nodes = make_nodes(5, seed=24)
    job = mock.job()
    job.task_groups[0].count = 2
    # cross-TG reserved-port overlap is a host-only shape
    from nomad_tpu.structs.structs import Port

    tg2 = copy.deepcopy(job.task_groups[0])
    tg2.name = "other"
    tg2.count = 1
    job.task_groups.append(tg2)
    for tg in job.task_groups:
        tg.tasks[0].resources.networks[0].reserved_ports = [
            Port(label="shared", value=12345)
        ]
    plans = run_pair(nodes, [job], lambda j: "service")
    assert "nomad.tpu_engine.fallback" in spy.calls
    assert plan_assignments(plans["binpack"][0]) == plan_assignments(plans["tpu_binpack"][0])


def test_parity_distinct_property_on_engine(monkeypatch):
    """distinct_property rides the engine (value-count feasibility carry):
    the fallback counter stays untouched and plans match the host."""
    spy = _CounterSpy(monkeypatch)
    nodes = make_nodes(12, seed=25)
    job = mock.job()
    job.task_groups[0].count = 6
    job.constraints.append(Constraint(operand="distinct_property",
                                      ltarget="${attr.rack}", rtarget="2"))
    plans = run_pair(nodes, [job], lambda j: "service")
    assert "nomad.tpu_engine.handled" in spy.calls
    assert "nomad.tpu_engine.fallback" not in spy.calls
    assert_parity(plans)
    # at most 2 allocs per rack value
    node_rack = {n.id: n.attributes["rack"] for n in nodes}
    rack_counts = {}
    for (_, _name), nid in plan_assignments(plans["tpu_binpack"][0]).items():
        r = node_rack[nid]
        rack_counts[r] = rack_counts.get(r, 0) + 1
    assert all(v <= 2 for v in rack_counts.values())


def test_parity_distinct_property_tg_level():
    """TG-level distinct_property counts only that TG's allocs."""
    nodes = make_nodes(16, seed=26)
    job = mock.job()
    tg0 = job.task_groups[0]
    job.task_groups = []
    for t in range(2):
        tg = copy.deepcopy(tg0)
        tg.name = f"tg{t}"
        tg.count = 3
        tg.constraints.append(Constraint(operand="distinct_property",
                                         ltarget="${attr.rack}"))
        job.task_groups.append(tg)
    assert_parity(run_pair(nodes, [job], lambda j: "service"))


def test_parity_distinct_property_destructive_update(monkeypatch):
    """DP + in-eval evictions: the host PropertySet's cleared-value refund
    quirk can't be replayed by exact counters, so the engine must fall
    back — and the plans must still match."""
    spy = _CounterSpy(monkeypatch)
    nodes = make_nodes(12, seed=28)
    results = {}
    for alg in ("binpack", "tpu_binpack"):
        h = Harness()
        h.state.scheduler_set_config(
            h.next_index(), SchedulerConfiguration(scheduler_algorithm=alg)
        )
        for n in nodes:
            h.state.upsert_node(h.next_index(), copy.deepcopy(n))
        job = mock.job()
        job.id = "dp-update"
        job.task_groups[0].count = 5
        job.constraints.append(Constraint(operand="distinct_property",
                                          ltarget="${attr.rack}", rtarget="3"))
        h.state.upsert_job(h.next_index(), copy.deepcopy(job))
        ev = Evaluation(priority=50, type="service",
                        triggered_by=EVAL_TRIGGER_JOB_REGISTER,
                        job_id=job.id, namespace="default")
        h.process("service", ev)
        job2 = copy.deepcopy(job)
        job2.version = 1
        job2.task_groups[0].tasks[0].config = {"command": "/bin/new"}
        h.state.upsert_job(h.next_index(), copy.deepcopy(job2))
        ev2 = Evaluation(priority=50, type="service",
                         triggered_by=EVAL_TRIGGER_JOB_REGISTER,
                         job_id=job.id, namespace="default")
        h.process("service", ev2)
        results[alg] = (h.plans, h.evals, h.create_evals)
    assert "nomad.tpu_engine.fallback" in spy.calls
    assert plan_assignments(results["binpack"][0]) == plan_assignments(results["tpu_binpack"][0])


def test_parity_distinct_property_overcommit():
    """More instances than distinct values: failures/blocked must match."""
    nodes = make_nodes(8, seed=27)
    job = mock.job()
    job.task_groups[0].count = 7
    job.constraints.append(Constraint(operand="distinct_property",
                                      ltarget="${node.datacenter}"))
    assert_parity(run_pair(nodes, [job], lambda j: "service"))


def test_parity_destructive_update_with_spread():
    """Regression: eviction must clear spread usage like the host's
    cleared_values path."""
    nodes = make_nodes(20, seed=20)
    job = mock.job()
    job.task_groups[0].count = 8
    job.datacenters = ["dc1", "dc2"]
    job.spreads = [Spread("${node.datacenter}", 100,
                          [SpreadTarget("dc1", 50), SpreadTarget("dc2", 50)])]
    results = {}
    for alg in ("binpack", "tpu_binpack"):
        h = Harness()
        h.state.scheduler_set_config(
            h.next_index(), SchedulerConfiguration(scheduler_algorithm=alg)
        )
        for n in nodes:
            h.state.upsert_node(h.next_index(), copy.deepcopy(n))
        j = copy.deepcopy(job)
        h.state.upsert_job(h.next_index(), j)
        ev = Evaluation(priority=j.priority, type=j.type,
                        triggered_by=EVAL_TRIGGER_JOB_REGISTER,
                        job_id=j.id, namespace=j.namespace)
        h.process("service", ev)
        # destructive update (config change) with the spread still in force
        j2 = copy.deepcopy(j)
        j2.task_groups[0].tasks[0].config = {"command": "/bin/new"}
        h.state.upsert_job(h.next_index(), j2)
        ev2 = Evaluation(priority=j2.priority, type=j2.type,
                         triggered_by=EVAL_TRIGGER_JOB_REGISTER,
                         job_id=j2.id, namespace=j2.namespace)
        h.process("service", ev2)
        results[alg] = h.plans
    assert plan_assignments(results["binpack"]) == plan_assignments(results["tpu_binpack"])


def test_parity_multi_tg_spread_weight_accumulation():
    """Regression: host SpreadIterator accumulates weight sums across TGs."""
    nodes = make_nodes(24, seed=21)
    job = mock.job()
    tg0 = job.task_groups[0]
    job.task_groups = []
    job.datacenters = ["dc1", "dc2"]
    job.spreads = [Spread("${node.datacenter}", 50,
                          [SpreadTarget("dc1", 60), SpreadTarget("dc2", 40)])]
    for t in range(3):
        tg = copy.deepcopy(tg0)
        tg.name = f"tg{t}"
        tg.count = 4
        job.task_groups.append(tg)
    assert_parity(run_pair(nodes, [job], lambda j: "service"))


def test_parity_spread_tg_then_plain_tg():
    """Regression: MaxInt32 limit widening is sticky across TGs in an eval."""
    nodes = make_nodes(32, seed=22)
    job = mock.job()
    tg0 = job.task_groups[0]
    job.task_groups = []
    spread_tg = copy.deepcopy(tg0)
    spread_tg.name = "spready"
    spread_tg.count = 3
    spread_tg.spreads = [Spread("${attr.rack}", 50, [])]
    plain_tg = copy.deepcopy(tg0)
    plain_tg.name = "plain"
    plain_tg.count = 6
    job.task_groups = [spread_tg, plain_tg]
    assert_parity(run_pair(nodes, [job], lambda j: "service"))


def test_parity_affinity_matching_no_node():
    """Regression: widening keys off stanza existence, not matches."""
    nodes = make_nodes(32, seed=23)
    job = mock.job()
    job.task_groups[0].count = 6
    job.affinities = [Affinity("${attr.rack}", "no-such-rack", "=", 100)]
    assert_parity(run_pair(nodes, [job], lambda j: "service"))


def test_parity_epoch_patched_encode_cache(monkeypatch):
    """The whole-eval encode cache's usage-epoch PATCH (engine.encode_eval
    + encode.epoch_usage_arrays): identically-shaped jobs scheduled
    SEQUENTIALLY — each commit rolls the usage epoch, so every eval
    after the first takes the patched-arrays path — must produce plans
    bit-identical to the host pipeline, and the patch counter must
    actually fire (no silent fallback to full re-encode)."""
    from nomad_tpu.utils import metrics

    calls = []
    orig = metrics.incr_counter

    def spy(name, value=1.0):
        calls.append(name)
        orig(name, value)

    monkeypatch.setattr(metrics, "incr_counter", spy)

    nodes = make_nodes(40, seed=9)
    jobs = []
    for i in range(6):
        j = mock.job()
        j.id = f"epoch-{i}"
        j.task_groups[0].count = 30
        # replace resources wholesale: the default mock task carries a
        # network ask, which (correctly) disqualifies the dense path
        from nomad_tpu.structs.structs import Resources
        j.task_groups[0].tasks[0].resources = Resources(cpu=120, memory_mb=96)
        jobs.append(j)
    plans = run_pair(nodes, jobs, lambda j: "service")
    assert "nomad.tpu_engine.encode_cache_patch" in calls, (
        "sequential same-shape jobs across commits should hit the "
        "epoch-patched cache path"
    )
    assert_parity(plans)


def test_parity_epoch_patched_with_spread_affinity(monkeypatch):
    """Same, with the full rank stack active (spread + affinity): the
    patch must leave the job-scoped spread/affinity arrays untouched
    while swapping only the usage pair."""
    from nomad_tpu.utils import metrics

    calls = []
    orig = metrics.incr_counter

    def spy(name, value=1.0):
        calls.append(name)
        orig(name, value)

    monkeypatch.setattr(metrics, "incr_counter", spy)

    nodes = make_nodes(40, seed=10)
    jobs = []
    for i in range(5):
        j = mock.job()
        j.id = f"epoch-sp-{i}"
        j.task_groups[0].count = 25
        from nomad_tpu.structs.structs import Resources
        j.task_groups[0].tasks[0].resources = Resources(cpu=100, memory_mb=64)
        j.task_groups[0].spreads = [Spread(
            attribute="${node.datacenter}", weight=50,
            spread_target=[SpreadTarget(value="dc1", percent=70),
                           SpreadTarget(value="dc2", percent=30)],
        )]
        j.task_groups[0].affinities = [Affinity(
            ltarget="${attr.kernel.name}", rtarget="linux",
            operand="=", weight=50,
        )]
        jobs.append(j)
    plans = run_pair(nodes, jobs, lambda j: "service")
    assert "nomad.tpu_engine.encode_cache_patch" in calls
    assert_parity(plans)


# ---------------------------------------------------------------------------
# Packed-mask layout (intscore packed lanes): fuzz the lane algebra the
# fused scan step relies on, and the chunked algorithm's deterministic
# fallback (bit-identical plans when every eval is chunk-ineligible).
# ---------------------------------------------------------------------------


def test_packed_lane_ring_cumsum_fuzz():
    """The fused scan's ONE packed ring cumsum must be bit-identical to
    the two separate int32 ring cumsums it replaced, for any masks and
    ring offset (totals bounded by n_pad < 2^15 => no inter-lane carry,
    and both selected ring branches are lane-wise non-negative)."""
    import numpy as np

    from nomad_tpu.tpu.intscore import (
        pack_count_lanes,
        unpack_count_hi,
        unpack_count_lo,
    )

    rng = random.Random(77)
    for trial in range(200):
        n = rng.choice([4, 16, 64, 256, 1024])
        low = np.asarray([rng.random() < 0.4 for _ in range(n)])
        feas = np.asarray([rng.random() < 0.7 for _ in range(n)])
        offset = rng.randrange(n)
        iota = np.arange(n, dtype=np.int32)

        def ring_cumsum(a_int):
            s_nat = np.cumsum(a_int, dtype=np.int32)
            total = s_nat[-1]
            before = np.sum(np.where(iota < offset, a_int, 0),
                            dtype=np.int32)
            return (
                np.where(iota >= offset, s_nat - before,
                         s_nat + (total - before)),
                total,
            )

        packed_cum, packed_total = ring_cumsum(pack_count_lanes(low, feas))
        low_cum, low_total = ring_cumsum(low.astype(np.int32))
        feas_cum, feas_total = ring_cumsum(feas.astype(np.int32))
        assert (unpack_count_lo(packed_cum) == low_cum).all()
        assert (unpack_count_hi(packed_cum) == feas_cum).all()
        assert unpack_count_lo(packed_total) == low_total
        assert unpack_count_hi(packed_total) == feas_total


def test_packed_feat_plane_roundtrip_fuzz():
    """pack_feat_planes/pack_presence_lanes round-trip bit-exactly: the
    unpacked lanes and the popcount num_terms match the unpacked int32
    arithmetic they fused away."""
    import numpy as np

    from nomad_tpu.tpu.intscore import (
        FEAT_AFF_BIT,
        FEAT_FEAS_BIT,
        pack_feat_planes,
        pack_presence_lanes,
        unpack_feat_lane,
    )

    rng = random.Random(13)
    for _ in range(100):
        g, n = rng.randint(1, 6), rng.choice([8, 64, 512])
        feas = np.asarray(
            [[rng.random() < 0.5 for _ in range(n)] for _ in range(g)])
        aff = np.asarray(
            [[rng.random() < 0.5 for _ in range(n)] for _ in range(g)])
        packed = pack_feat_planes(feas, aff)
        assert packed.dtype == np.uint8
        assert (unpack_feat_lane(packed, FEAT_FEAS_BIT) == feas).all()
        assert (unpack_feat_lane(packed, FEAT_AFF_BIT) == aff).all()
        # zero-G affinity specialization: bit1 lane stays all-zero
        sparse = pack_feat_planes(feas, np.zeros((0, n), bool))
        assert (unpack_feat_lane(sparse, FEAT_AFF_BIT) == False).all()  # noqa: E712

        masks = [np.asarray([rng.random() < 0.5 for _ in range(n)])
                 for _ in range(4)]
        presence = pack_presence_lanes(*masks)
        popcounts = np.asarray(
            [bin(int(v)).count("1") for v in presence.reshape(-1)]
        ).reshape(presence.shape)
        expected = sum(m.astype(np.int32) for m in masks)
        assert (popcounts == expected).all()


def test_parity_chunked_algorithm_deterministic_fallback():
    """tpu_binpack_chunked on the deterministic harness: every eval is
    chunk-INELIGIBLE (int-mode encode), so the tier must fall back to
    the bit-parity scan and produce plans identical to the host oracle
    — the preemption/deficit-carry gate exercised end to end."""
    nodes = make_nodes(25, seed=21)
    jobs = []
    for i in range(3):
        j = mock.job()
        j.id = f"chunked-fb-{i}"
        j.task_groups[0].count = 10
        jobs.append(j)

    plans = {}
    for alg in ("binpack", "tpu_binpack_chunked"):
        h = Harness()
        h.state.scheduler_set_config(
            h.next_index(), SchedulerConfiguration(scheduler_algorithm=alg)
        )
        for n in nodes:
            h.state.upsert_node(h.next_index(), copy.deepcopy(n))
        for job in jobs:
            h.state.upsert_job(h.next_index(), copy.deepcopy(job))
        for job in jobs:
            ev = Evaluation(
                priority=job.priority,
                type=job.type,
                triggered_by=EVAL_TRIGGER_JOB_REGISTER,
                job_id=job.id,
                namespace=job.namespace,
            )
            h.process("service", ev)
        plans[alg] = (h.plans, h.evals, h.create_evals)

    host_plans, _, _ = plans["binpack"]
    ch_plans, _, _ = plans["tpu_binpack_chunked"]
    assert len(host_plans) == len(ch_plans)
    assert plan_assignments(host_plans) == plan_assignments(ch_plans)
