"""nomad-trace: eval lifecycle records, the liveness watchdog, and the
/v1/trace surface.

The lifecycle tests drive a bare EvalBroker (the stamping call sites are
inside enqueue/dequeue/ack/nack, so no server is needed); the watchdog
test runs a real in-proc Server whose scheduler is replaced by a stub
that parks mid-invoke — the synthetic form of round 5's stall, where
evals sat unacked for minutes with placement flat and nothing alarmed.
"""
import logging
import threading
import time

import pytest

from nomad_tpu import mock
from nomad_tpu.server.eval_broker import EvalBroker
from nomad_tpu.structs.structs import EVAL_STATUS_PENDING, Evaluation
from nomad_tpu.trace import lifecycle
from nomad_tpu.utils import metrics


def _gauges():
    return {g["Name"]: g["Value"]
            for g in metrics.global_sink().summary()["Gauges"]}


def spin_until(fn, timeout=30.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if fn():
            return
        time.sleep(0.01)
    raise AssertionError(f"timed out: {msg}")


# ---------------------------------------------------------------------------
# lifecycle records through the broker
# ---------------------------------------------------------------------------


def test_broker_round_trip_produces_one_acked_record():
    lifecycle.reset()
    broker = EvalBroker(nack_timeout=5.0)
    broker.set_enabled(True)
    ev = Evaluation(job_id="trace-job", type="service",
                    status=EVAL_STATUS_PENDING, priority=50)
    broker.enqueue(ev)
    assert lifecycle.summary()["inflight"] == 1

    got, token = broker.dequeue(["service"], timeout=2.0)
    assert got is not None and got.id == ev.id
    broker.ack(ev.id, token)

    s = lifecycle.summary()
    assert s["inflight"] == 0
    assert s["completed"] == 1
    assert s["outcomes"]["ack"] == 1
    assert s["eval_ms_p50"] > 0

    rec = lifecycle.snapshot()["recent"][-1]
    assert rec["eval_id"] == ev.id
    assert rec["job_id"] == "trace-job"
    assert rec["outcome"] == "ack"
    assert rec["attempt"] == 1
    assert rec["queue_ms"] is not None and rec["queue_ms"] >= 0
    assert rec["total_ms"] >= rec["queue_ms"]


def test_nack_closes_record_and_redelivery_opens_fresh_one():
    lifecycle.reset()
    broker = EvalBroker(nack_timeout=5.0, delivery_limit=10,
                        initial_nack_delay=0.02, subsequent_nack_delay=0.05)
    broker.set_enabled(True)
    ev = Evaluation(job_id="trace-nack", type="service",
                    status=EVAL_STATUS_PENDING, priority=50)
    broker.enqueue(ev)
    got, token = broker.dequeue(["service"], timeout=2.0)
    broker.nack(got.id, token)

    s = lifecycle.summary()
    assert s["outcomes"]["nack"] == 1

    # after the nack delay the broker re-enqueues: a FRESH record opens
    # carrying the bumped delivery counter as the OCC attempt number
    got2, token2 = broker.dequeue(["service"], timeout=5.0)
    assert got2 is not None and got2.id == ev.id
    broker.ack(got2.id, token2)
    recs = lifecycle.snapshot()["recent"]
    assert [r["outcome"] for r in recs] == ["nack", "ack"]
    assert recs[-1]["attempt"] == 2


def test_publish_gauges_exports_tail_latency():
    lifecycle.reset()
    metrics.global_sink().reset()
    broker = EvalBroker(nack_timeout=5.0)
    broker.set_enabled(True)
    ev = Evaluation(job_id="trace-gauge", type="service",
                    status=EVAL_STATUS_PENDING, priority=50)
    broker.enqueue(ev)
    got, token = broker.dequeue(["service"], timeout=2.0)
    broker.ack(ev.id, token)

    lifecycle.publish_gauges()
    g = _gauges()
    assert g["nomad.trace.eval_ms.p50"] > 0
    assert g["nomad.trace.inflight"] == 0
    assert "nomad.trace.slowest_inflight_ms" in g


# ---------------------------------------------------------------------------
# liveness watchdog on a synthetic stall
# ---------------------------------------------------------------------------


class _StuckScheduler:
    """Stands in for every scheduler type: parks mid-invoke until released."""

    started = threading.Event()
    release = threading.Event()

    def __init__(self, *a, **kw):
        pass

    def process(self, evaluation):
        _StuckScheduler.started.set()
        _StuckScheduler.release.wait(timeout=60)


def test_watchdog_dumps_on_stalled_eval(monkeypatch, caplog):
    from nomad_tpu.server.server import Server, ServerConfig

    lifecycle.reset()
    _StuckScheduler.started.clear()
    _StuckScheduler.release.clear()
    monkeypatch.setattr("nomad_tpu.server.worker.new_scheduler",
                        lambda *a, **kw: _StuckScheduler())

    server = Server(ServerConfig(
        num_schedulers=1, device_batch=0,
        heartbeat_min_ttl=3600, heartbeat_max_ttl=7200,
        watchdog_interval=0,  # tick manually for determinism
    ))
    server.watchdog.stall_after = 0.3
    server.start()
    try:
        server.register_job(mock.job())
        assert _StuckScheduler.started.wait(timeout=15), \
            "worker never invoked the stub scheduler"

        # first tick establishes the placed-count baseline
        assert server.watchdog.tick() is False
        time.sleep(0.4)
        with caplog.at_level(logging.WARNING,
                             logger="nomad_tpu.trace.watchdog"):
            fired = server.watchdog.tick()
        assert fired is True
        assert server.watchdog.fired == 1

        dump = caplog.text
        assert "liveness watchdog" in dump
        assert "total_unacked" in dump           # broker stats
        assert "invoke_scheduler" in dump        # per-worker current span
        assert "slowest in-flight" in dump
        assert "thread stacks" in dump

        spans = server.watchdog.worker_spans()
        assert any(s["span"] is not None
                   and s["span"]["phase"] == "invoke_scheduler"
                   for s in spans)

        # the stuck eval shows up as a nonzero slowest-in-flight gauge
        metrics.global_sink().reset()
        lifecycle.publish_gauges()
        g = _gauges()
        assert g["nomad.trace.slowest_inflight_ms"] > 300
        assert g["nomad.trace.inflight"] >= 1

        # rate limit: an immediate re-tick inside the window stays quiet
        assert server.watchdog.tick() is False
    finally:
        _StuckScheduler.release.set()
        server.stop()


# ---------------------------------------------------------------------------
# /v1/trace endpoint
# ---------------------------------------------------------------------------


def test_v1_trace_endpoint_end_to_end():
    import json
    import urllib.request

    from nomad_tpu.agent import Agent, AgentConfig

    lifecycle.reset()
    agent = Agent(AgentConfig(dev_mode=True, num_schedulers=2, name="trace1"))
    agent.start()
    try:
        agent.server.register_job(mock.job())
        spin_until(lambda: lifecycle.summary()["completed"] >= 1,
                   msg="an eval completing")
        with urllib.request.urlopen(
                agent.http_addr + "/v1/trace?recent=8", timeout=30) as resp:
            out = json.loads(resp.read().decode())
        assert out["completed"] >= 1
        assert "eval_ms_p50" in out and "slowest_inflight_ms" in out
        assert isinstance(out["inflight_evals"], list)
        assert isinstance(out["recent"], list) and len(out["recent"]) <= 8
        assert out["recent"][-1]["outcome"] in ("ack", "nack", "failed")
        # agent runs a server: worker spans ride along
        assert "workers" in out
    finally:
        agent.shutdown()


# ---------------------------------------------------------------------------
# pipeline stage spans (nomad-pipeline rides on nomad-trace)
# ---------------------------------------------------------------------------


def test_pipeline_stage_spans_and_summary():
    lifecycle.reset()
    with lifecycle.pipeline_stage("encode", "wave-1"):
        # depth is visible while the stage is open
        assert lifecycle.pipeline_summary()["encode"]["depth"] == 1
        time.sleep(0.01)
    t0 = lifecycle.pipeline_now()
    lifecycle.pipeline_record("commit", "wave-1", t0, t0 + 0.005)

    spans = lifecycle.pipeline_spans()
    assert ("encode", "wave-1") in {(s, w) for (s, w, _, _) in spans}
    assert lifecycle.pipeline_spans("commit") and \
        not lifecycle.pipeline_spans("evaluate")

    summ = lifecycle.pipeline_summary()
    assert summ["encode"]["depth"] == 0
    assert summ["encode"]["count"] == 1
    assert summ["commit"]["count"] == 1
    assert summ["commit"]["latency_ms_p95"] >= 4.0
    # every declared stage reports, populated or not
    assert set(lifecycle.PIPELINE_STAGES) <= set(summ)
    # the /v1/trace payload carries the same block
    assert lifecycle.snapshot()["pipeline"]["encode"]["count"] == 1


def test_pipeline_gauges_published():
    lifecycle.reset()
    with lifecycle.pipeline_stage("dispatch", "wave-g"):
        pass
    lifecycle.publish_gauges()
    g = _gauges()
    assert g["nomad.trace.pipeline.dispatch.count"] == 1
    assert g["nomad.trace.pipeline.dispatch.depth"] == 0
    assert "nomad.trace.pipeline.dispatch.latency_ms_p95" in g
