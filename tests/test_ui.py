"""Web UI tests — the reference ships 108 UI test files against Mirage
(a fake /v1 API); our no-build SPA is exercised the inverse way: a REAL
agent serves both the bundle and /v1, and these tests assert (a) the
bundle ships every view and its wiring, and (b) every endpoint the SPA
consumes answers with the shapes the JS destructures — the API-contract
half of UI testing, without a JS runtime.
"""
import json
import time
import urllib.request

import pytest

from nomad_tpu import mock
from nomad_tpu.agent.agent import Agent, AgentConfig


def http(agent, method, path, body=None, raw=False):
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(
        agent.http_addr + path, method=method, data=data,
        headers={"Content-Type": "application/json"} if body else {},
    )
    with urllib.request.urlopen(req, timeout=10) as resp:
        payload = resp.read()
    if raw:
        return payload
    return json.loads(payload) if payload else None


@pytest.fixture
def agent():
    a = Agent(AgentConfig(
        name="ui-agent", gossip_enabled=False, client_enabled=True,
        dev_mode=True, num_schedulers=1,
    ))
    a.start()
    yield a
    a.shutdown()


def wait_until(fn, timeout=30.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if fn():
            return
        time.sleep(0.05)
    raise AssertionError(f"timed out waiting for {msg}")


HCL = """
job "ui-smoke" {
  datacenters = ["dc1"]
  group "g" {
    count = 1
    task "t" {
      driver = "raw_exec"
      config { command = "/bin/sh" args = ["-c", "sleep 60"] }
      resources { cpu = 50 memory = 32 }
    }
  }
}
"""


class TestUIBundle:
    def test_spa_served_with_all_views(self, agent):
        html = http(agent, "GET", "/ui/", raw=True).decode()
        # nav entries
        for view in ("jobs", "run", "nodes", "topo", "allocs", "evals",
                     "deploys", "servers"):
            assert f'"{view}"' in html, f"view {view} missing from bundle"
        # page implementations + core wiring
        for marker in ("async jobs()", "async run(", "async function api(",
                       "data-stop-job", "plan-btn", "run-btn",
                       "jobspec", "WebSocket", "log-view", "X-Nomad-Token",
                       # r4: live cpu/mem sparklines + deployment actions
                       "function spark(", "SPARK_WINDOW", "polyline",
                       "data-dep-promote", "data-dep-fail",
                       "deploymentAction",
                       # r4: cluster topology view
                       "async topo()", "topo-node", "CPUShares"):
            assert marker in html, f"bundle missing {marker!r}"

    def test_ui_route_without_trailing_slash(self, agent):
        html = http(agent, "GET", "/ui", raw=True).decode()
        assert "<title" in html or "nomad-tpu" in html


class TestUIEndpointContract:
    """Every /v1 call the SPA's pages make, against a live agent."""

    def test_job_run_flow_parse_plan_register(self, agent):
        # the Run Job view: parse HCL -> plan preview -> register
        job = http(agent, "POST", "/v1/jobs/parse", {"JobHCL": HCL})
        assert job["ID"] == "ui-smoke"
        plan = http(agent, "PUT", f"/v1/job/{job['ID']}/plan",
                    {"Job": job, "Diff": True})
        assert "Annotations" in plan or "Diff" in plan or plan
        out = http(agent, "POST", "/v1/jobs", {"Job": job})
        assert out.get("EvalID")

        # jobs list page shape
        wait_until(lambda: any(j["ID"] == "ui-smoke"
                               for j in http(agent, "GET", "/v1/jobs")),
                   msg="job listed")
        jobs = http(agent, "GET", "/v1/jobs")
        entry = next(j for j in jobs if j["ID"] == "ui-smoke")
        for key in ("ID", "Type", "Priority", "Status"):
            assert key in entry

        # job detail page shape
        detail = http(agent, "GET", "/v1/job/ui-smoke")
        for key in ("ID", "Name", "Type", "Priority", "Datacenters"):
            assert key in detail
        allocs = http(agent, "GET", "/v1/job/ui-smoke/allocations?all=true")
        evals = http(agent, "GET", "/v1/job/ui-smoke/evaluations")
        assert isinstance(allocs, list) and isinstance(evals, list)
        assert evals and {"ID", "TriggeredBy", "Status"} <= set(evals[0])

        # alloc list/detail shapes once placed
        wait_until(lambda: http(agent, "GET", "/v1/allocations"),
                   msg="allocations listed")
        allocs = http(agent, "GET", "/v1/allocations")
        a = allocs[0]
        for key in ("ID", "JobID", "TaskGroup", "DesiredStatus",
                    "ClientStatus", "NodeID"):
            assert key in a
        detail = http(agent, "GET", f"/v1/allocation/{a['ID']}")
        assert detail["ID"] == a["ID"]

    def test_nodes_and_servers_pages(self, agent):
        nodes = http(agent, "GET", "/v1/nodes")
        assert nodes and {"ID", "Name", "Status"} <= set(nodes[0])
        node = http(agent, "GET", f"/v1/node/{nodes[0]['ID']}")
        assert "Attributes" in node
        members = http(agent, "GET", "/v1/agent/members")
        assert "Members" in members or isinstance(members, list)

    def test_evals_and_deployments_pages(self, agent):
        evals = http(agent, "GET", "/v1/evaluations")
        assert isinstance(evals, list)
        deploys = http(agent, "GET", "/v1/deployments")
        assert isinstance(deploys, list)

    def test_stop_job_button_endpoint(self, agent):
        job = http(agent, "POST", "/v1/jobs/parse", {"JobHCL": HCL})
        http(agent, "POST", "/v1/jobs", {"Job": job})
        wait_until(lambda: any(j["ID"] == "ui-smoke"
                               for j in http(agent, "GET", "/v1/jobs")),
                   msg="job listed")
        out = http(agent, "DELETE", "/v1/job/ui-smoke")
        assert out.get("EvalID")
        wait_until(
            lambda: http(agent, "GET", "/v1/job/ui-smoke")["Stop"] is True,
            msg="job stopped",
        )
