"""nomad-watch tests: hub wakeup registry, blocking-query semantics,
follower stale reads, chaos degradation, and the 5K-watcher stress —
reference blocking_query.go / state_store.go watchsets / rpc.go
allowStale."""
import threading
import time

import pytest

from nomad_tpu import mock
from nomad_tpu.chaos.injector import ChaosInjector
from nomad_tpu.rpc import RPCClient, RPCError, RPCServer, bind_server
from nomad_tpu.server import InProcRaft, Server, ServerConfig
from nomad_tpu.server.fsm import EVAL_UPDATE
from nomad_tpu.structs.structs import (
    EVAL_STATUS_COMPLETE,
    QueryMeta,
    QueryOptions,
)
from nomad_tpu.watch import WatchHub, WatchLimitError, blocking_read
from nomad_tpu.watch.stale import StaleReader, follower_lag_ms, read_meta


def wait_for(cond, timeout=15.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(0.02)
    raise AssertionError(f"timed out waiting for {msg}")


def _beacon(i=0):
    ev = mock.eval()
    ev.id = f"watch-beacon-{i:04d}"
    ev.status = EVAL_STATUS_COMPLETE  # terminal: the broker ignores it
    return ev


# ---------------------------------------------------------------------------
# hub units
# ---------------------------------------------------------------------------


def test_hub_per_key_vs_per_table_wakeup():
    hub = WatchHub(coalesce_ms=0)  # synchronous drain
    try:
        h_table = hub.subscribe("evals")
        h_a = hub.subscribe("evals", key="a")
        h_b = hub.subscribe("evals", key="b")
        h_other = hub.subscribe("nodes")
        assert hub.watcher_count() == 4

        hub.notify(5, [("evals", "a")])
        assert h_table.triggered() and h_table.wake_index == 5
        assert h_a.triggered() and h_a.wake_index == 5
        assert not h_b.triggered()
        assert not h_other.triggered()
        # woken handles are one-shot: removed from the registry
        assert hub.watcher_count() == 2

        # key=None touch = bulk write: wakes the remaining row-level too
        hub.notify(6, [("evals", None)])
        assert h_b.triggered() and h_b.wake_index == 6
        assert not h_other.triggered()
        assert hub.watcher_count() == 1
    finally:
        hub.close()


def test_hub_coalesces_notify_storm():
    hub = WatchHub(coalesce_ms=40)
    try:
        handle = hub.subscribe("evals")
        seen = []
        hub.add_callback(lambda tables, index: seen.append((tables, index)))
        for i in range(1, 21):
            hub.notify(i, [("evals", f"k{i}")])
        assert handle.wait(5.0), "coalesced flush never fired"
        assert handle.wake_index == 20  # flush carries the LATEST index
        wait_for(lambda: hub.stats()["pending_tables"] == 0,
                 msg="pending drained")
        st = hub.stats()
        assert st["notifies"] == 20
        # 20 notifies inside one 40ms window flush once or twice, not 20x
        assert 1 <= st["flushes"] <= 3, st
        assert st["coalesce_ratio"] >= 20 / 3
        assert st["wakeups"] == 1  # the single parked handle woke ONCE
        assert seen and seen[-1][0] == ("evals",) and seen[-1][1] == 20
    finally:
        hub.close()


def test_hub_bounded_registry_rejects_then_recovers():
    hub = WatchHub(coalesce_ms=0, max_watchers=4)
    try:
        handles = [hub.subscribe("jobs") for _ in range(4)]
        with pytest.raises(WatchLimitError):
            hub.subscribe("jobs")
        assert hub.stats()["rejected"] == 1
        hub.unsubscribe(handles[0])
        hub.subscribe("jobs")  # slot freed
        assert hub.watcher_count() == 4
        # unsubscribe is idempotent, including for already-woken handles
        hub.notify(1, [("jobs", None)])
        for h in handles[1:]:
            hub.unsubscribe(h)
        assert hub.watcher_count() == 0
    finally:
        hub.close()


# ---------------------------------------------------------------------------
# blocking semantics (in-process, through the real FSM notify wiring)
# ---------------------------------------------------------------------------


@pytest.fixture
def quiet_server():
    s = Server(ServerConfig(num_schedulers=0))
    yield s
    s.watch_hub.close()


def _read_evals(server, opts):
    return blocking_read(
        lambda: server.fsm.state, server.watch_hub,
        lambda st: {e.id for e in st.evals()}, "evals", opts,
    )


def test_blocking_read_immediate_when_index_passed(quiet_server):
    s = quiet_server
    idx, _ = s.raft_apply(EVAL_UPDATE, [_beacon(0)])
    t0 = time.monotonic()
    result, meta = _read_evals(s, QueryOptions(min_query_index=idx - 1,
                                               max_query_time=10.0))
    assert time.monotonic() - t0 < 1.0  # no park
    assert "watch-beacon-0000" in result
    assert meta.index == idx
    assert isinstance(meta, QueryMeta)


def test_blocking_read_parks_then_wakes_on_apply(quiet_server):
    s = quiet_server
    idx, _ = s.raft_apply(EVAL_UPDATE, [_beacon(0)])
    out = {}

    def park():
        out["result"], out["meta"] = _read_evals(
            s, QueryOptions(min_query_index=idx, max_query_time=30.0))

    t = threading.Thread(target=park)
    t0 = time.monotonic()
    t.start()
    wait_for(lambda: s.watch_hub.watcher_count() == 1, msg="watcher parked")
    s.raft_apply(EVAL_UPDATE, [_beacon(1)])
    t.join(timeout=10.0)
    assert not t.is_alive(), "watcher never woke"
    elapsed = time.monotonic() - t0
    assert elapsed < 10.0  # woke via notify, nowhere near max_query_time
    assert "watch-beacon-0001" in out["result"]
    assert out["meta"].index > idx


def test_blocking_read_deadline_returns_current_index(quiet_server):
    s = quiet_server
    idx, _ = s.raft_apply(EVAL_UPDATE, [_beacon(0)])
    t0 = time.monotonic()
    result, meta = _read_evals(
        s, QueryOptions(min_query_index=idx + 100, max_query_time=0.4))
    elapsed = time.monotonic() - t0
    assert 0.3 <= elapsed < 5.0  # held until deadline, then answered
    assert meta.index == idx  # CURRENT index, the client's next floor
    assert "watch-beacon-0000" in result


def test_blocking_read_full_registry_degrades_to_plain_read(quiet_server):
    s = quiet_server
    idx, _ = s.raft_apply(EVAL_UPDATE, [_beacon(0)])
    s.watch_hub.max_watchers = 0  # force WatchLimitError on subscribe
    t0 = time.monotonic()
    result, meta = _read_evals(
        s, QueryOptions(min_query_index=idx, max_query_time=30.0))
    assert time.monotonic() - t0 < 1.0  # answered now, no unbounded park
    assert meta.index == idx
    assert s.watch_hub.stats()["rejected"] == 1


# ---------------------------------------------------------------------------
# chaos: dropped watch_notify degrades to the deadline re-query
# ---------------------------------------------------------------------------


def test_dropped_notify_degrades_to_deadline_requery(quiet_server):
    """Arm watch_notify at prob=1.0: every post-apply notification is
    dropped. A parked watcher must still return by its max_query_time —
    late, but with the CURRENT index and fresh data (never wedged, never
    stale)."""
    s = quiet_server
    idx, _ = s.raft_apply(EVAL_UPDATE, [_beacon(0)])
    # drain beacon-0's coalesce window first: its pending flush would
    # otherwise deliver the wakeup the armed fault is supposed to drop
    wait_for(lambda: s.watch_hub.stats()["pending_tables"] == 0,
             msg="pre-arm flush drained")
    inj = ChaosInjector(seed=7)
    inj.arm("watch_notify", mode="fail", prob=1.0)
    try:
        out = {}

        def park():
            out["result"], out["meta"] = _read_evals(
                s, QueryOptions(min_query_index=idx, max_query_time=1.2))

        t = threading.Thread(target=park)
        t0 = time.monotonic()
        t.start()
        wait_for(lambda: s.watch_hub.watcher_count() == 1,
                 msg="watcher parked")
        s.raft_apply(EVAL_UPDATE, [_beacon(1)])  # notify dropped
        t.join(timeout=15.0)
        assert not t.is_alive(), "dropped notify wedged the watcher"
        elapsed = time.monotonic() - t0
        assert elapsed >= 1.0  # no wakeup arrived: it rode the deadline
        # ... and the deadline re-query still surfaced the new write
        assert "watch-beacon-0001" in out["result"]
        assert out["meta"].index > idx
        assert inj.fires("watch_notify") >= 1
        assert s.watch_hub.stats()["dropped_notifies"] >= 1
    finally:
        inj.disarm_all()


# ---------------------------------------------------------------------------
# over the wire: QueryMeta stamping + follower stale reads
# ---------------------------------------------------------------------------


@pytest.fixture
def wire_pair():
    """Leader + follower sharing an InProcRaft, each behind a real
    RPCServer (the test_rpc.py forwarding topology)."""
    raft = InProcRaft()
    leader = Server(ServerConfig(num_schedulers=0), raft=raft, name="s1")
    follower = Server(ServerConfig(num_schedulers=0), raft=raft, name="s2")
    rpc_l = RPCServer()
    bind_server(leader, rpc_l)
    rpc_l.is_leader = lambda: leader.is_leader
    rpc_l.start()
    rpc_f = RPCServer()
    bind_server(follower, rpc_f)
    rpc_f.is_leader = lambda: follower.is_leader
    rpc_f.leader_addr = rpc_l.addr
    rpc_f.start()
    yield leader, follower, rpc_l, rpc_f
    rpc_f.stop()
    rpc_l.stop()
    leader.watch_hub.close()
    follower.watch_hub.close()


def test_rpc_reads_stamp_query_meta_and_stay_back_compat(wire_pair):
    leader, follower, rpc_l, rpc_f = wire_pair
    c = RPCClient(*rpc_l.addr)
    try:
        idx = c.call("Eval.Update", [_beacon(0)])
        # legacy shape: no query_opts -> bare result, old callers untouched
        bare = c.call("Eval.GetEval", "watch-beacon-0000")
        assert bare.id == "watch-beacon-0000"
        # opted-in shape: [result, QueryMeta] with the index stamped
        ev, meta = c.call("Eval.GetEval", "watch-beacon-0000", QueryOptions())
        assert ev.id == "watch-beacon-0000"
        assert isinstance(meta, QueryMeta)
        assert meta.index == idx
        assert meta.known_leader
        assert meta.follower_lag_ms == 0.0  # served by the leader
    finally:
        c.close()


def test_follower_serves_stale_reads_locally(wire_pair):
    leader, follower, rpc_l, rpc_f = wire_pair
    lead_c = RPCClient(*rpc_l.addr)
    foll_c = RPCClient(*rpc_f.addr)
    try:
        idx = lead_c.call("Eval.Update", [_beacon(0)])
        # point the follower's forwarding at a dead address: any request
        # that still forwards now fails, so a success PROVES local serving
        rpc_f.leader_addr = ("127.0.0.1", 1)
        with pytest.raises(RPCError):
            foll_c.call("Eval.List", QueryOptions(), timeout=3.0)
        evs, meta = foll_c.call("Eval.List", QueryOptions(), stale=True)
        assert any(e.id == "watch-beacon-0000" for e in evs)
        assert meta.index == idx
        assert meta.known_leader  # leader_addr is set (even if dead)
        assert meta.follower_lag_ms >= 0.0
        assert follower_lag_ms(leader) == 0.0
        assert read_meta(leader).known_leader
    finally:
        rpc_f.leader_addr = rpc_l.addr
        foll_c.close()
        lead_c.close()


def test_follower_stale_watch_wakes_on_replication(wire_pair):
    """min_query_index on a stale read parks on the FOLLOWER's hub and
    wakes when the follower's own FSM applies the write — the
    stale-but-index-consistent contract."""
    leader, follower, rpc_l, rpc_f = wire_pair
    lead_c = RPCClient(*rpc_l.addr)
    try:
        idx = lead_c.call("Eval.Update", [_beacon(0)])
        out = {}

        def park():
            c = RPCClient(*rpc_f.addr)
            try:
                reader = StaleReader(c)
                reader.last_index = idx
                out["result"], out["meta"] = reader.watch(
                    "Eval.List", max_query_time=30.0)
                out["chained"] = reader.last_index
            finally:
                c.close()

        t = threading.Thread(target=park)
        t0 = time.monotonic()
        t.start()
        wait_for(lambda: follower.watch_hub.watcher_count() == 1,
                 msg="watcher parked on the follower's hub")
        lead_c.call("Eval.Update", [_beacon(1)])
        t.join(timeout=15.0)
        assert not t.is_alive(), "follower watcher never woke"
        assert time.monotonic() - t0 < 15.0
        assert any(e.id == "watch-beacon-0001" for e in out["result"])
        assert out["meta"].index > idx
        assert out["chained"] == out["meta"].index
    finally:
        lead_c.close()


# ---------------------------------------------------------------------------
# 5K-watcher stress: zero lost wakeups, race-witness armed
# ---------------------------------------------------------------------------


def test_5k_watchers_zero_lost_wakeups_race_armed():
    """Park 5000 blocking readers on one hub, land ONE write, and require
    every single reader to wake with the new index well before its
    deadline — a lost wakeup shows up as a deadline-length straggler.
    The Eraser race witness is armed for the whole run (the hub's
    registry dict is minted through tracked_dict AFTER arming), so the
    wakeup storm is also a data-race proof over the hub's shared state."""
    from nomad_tpu.utils import race_witness

    witness = race_witness.arm()
    old_stack = threading.stack_size(256 * 1024)  # 5K threads, small stacks
    try:
        server = Server(ServerConfig(num_schedulers=0))
        try:
            idx, _ = server.raft_apply(EVAL_UPDATE, [_beacon(0)])
            n = 5000
            results = [None] * n
            deadline_s = 120.0

            def park(slot):
                results[slot] = _read_evals(
                    server, QueryOptions(min_query_index=idx,
                                         max_query_time=deadline_s))

            threads = [threading.Thread(target=park, args=(i,), daemon=True)
                       for i in range(n)]
            for t in threads:
                t.start()
            wait_for(lambda: server.watch_hub.watcher_count() == n,
                     timeout=90.0, msg=f"{n} watchers parked")

            t_commit = time.monotonic()
            new_idx, _ = server.raft_apply(EVAL_UPDATE, [_beacon(1)])
            for t in threads:
                t.join(timeout=60.0)
            wake_s = time.monotonic() - t_commit
            stragglers = [t for t in threads if t.is_alive()]
            assert not stragglers, f"{len(stragglers)} watchers lost wakeup"
            # every reader saw the post-commit index — none rode the
            # deadline, none returned the stale pre-commit view
            assert wake_s < deadline_s / 2, wake_s
            for i, out in enumerate(results):
                assert out is not None, f"watcher {i} returned nothing"
                result, meta = out
                assert meta.index >= new_idx, (i, meta.index, new_idx)
                assert "watch-beacon-0001" in result, i
            st = server.watch_hub.stats()
            assert st["watchers"] == 0  # registry fully drained
            assert st["wakeups"] >= n
        finally:
            server.watch_hub.close()

        rw = witness.stats()
        assert rw["violations"] == 0, witness.field_report()
        assert rw["accesses"] > 0
    finally:
        threading.stack_size(old_stack)
        race_witness.disarm()
