"""Wave throughput: mini c1m-mixed end-to-end through the async pipeline.

Tier-1 guard for the r06 perf round. The headline bench (bench.py
bench_c1m_system) depends on three properties that used to regress
silently:

  1. WAVE FORMATION — the broker/gather cadence hands workers enough
     concurrent evals that device dispatches actually fill the eval
     batch (r05 shipped 328 evals over 21 dispatches against a 64 cap
     because the gather window amputated cohorts mid-encode).
  2. ATTRIBUTION COVERAGE — the flight recorder's critical-path ledger
     explains >=90% of the wall, INCLUDING the instrumented ``idle``
     component (r05's ~500s worker-parked gap was invisible because
     idle time was nobody's span).
  3. DEVICE/HOST PARITY — the batched device path places the same
     allocation map as the host oracle, so none of the cadence work
     above bought throughput by changing answers.

Scale is deliberately small (2K placements over 50 nodes) so this stays
tier-1; bench.py runs the same assertions at 1M via BENCH_r06.json.
"""
import copy
import time

from nomad_tpu import mock
from nomad_tpu.server import Server, ServerConfig
from nomad_tpu.server.fsm import NODE_REGISTER
from nomad_tpu.structs.structs import Resources
from nomad_tpu.trace import attribution, lifecycle


def wait_for(cond, timeout=60.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(0.02)
    raise AssertionError(f"timed out waiting for {msg}")


def mini_node(i, cpu=4000, mem=8192):
    n = mock.node()
    n.name = f"wave-{i}"
    n.node_resources.cpu_shares = cpu
    n.node_resources.memory_mb = mem
    n.compute_class()
    return n


def mini_job(job_id, count=50, cpu=50, mem=64):
    j = mock.job()
    j.id = job_id
    j.task_groups[0].count = count
    j.task_groups[0].tasks[0].resources = Resources(cpu=cpu, memory_mb=mem)
    return j


def placed_count(server, jobs):
    return sum(
        len(server.fsm.state.allocs_by_job(j.namespace, j.id, True))
        for j in jobs
    )


def test_mini_c1m_wave_fill_and_idle_coverage():
    """2K placements (40 jobs x 50) flood a server with 8 workers and a
    4-eval device batch. Asserts full wave formation (mean eval batch >=
    half the cap) and that the bottleneck ledger covers >=90% of the
    window with the instrumented ``idle`` component present — workers
    idled between server start and the flood, and that time must be a
    named span, not an attribution hole."""
    lifecycle.reset()
    server = Server(ServerConfig(
        num_schedulers=8, deterministic=True, device_batch=4,
        device_min_placements=0,
        heartbeat_min_ttl=3600, heartbeat_max_ttl=7200,
    ))
    server.start()
    try:
        for i in range(50):
            server.raft_apply(NODE_REGISTER, mini_node(i))
        # let the workers visibly idle-poll before the flood: the idle
        # spans they record on their first dequeue are what satellite 1
        # promises the attribution ledger
        time.sleep(0.8)

        jobs = [mini_job(f"mini-c1m-{i}") for i in range(40)]
        for j in jobs:
            server.register_job(j)

        wait_for(lambda: placed_count(server, jobs) >= 2000,
                 timeout=180.0, msg="2000 placements")
        wait_for(
            lambda: server.eval_broker.stats().get("total_unacked", 0) == 0,
            timeout=30.0, msg="broker drained",
        )

        # (1) wave formation: dispatches filled at least half the batch
        # on average — 40 concurrent evals against a 4-eval cap must not
        # degenerate into single-eval waves
        stats = server.device_batcher.stats
        assert stats["dispatches"] > 0, stats
        mean_batch = stats["evals"] / stats["dispatches"]
        assert mean_batch >= 2.0, (
            f"waves did not fill: {stats['evals']} evals over "
            f"{stats['dispatches']} dispatches (mean {mean_batch:.2f}, "
            f"cap 4) — gather cadence regression"
        )
        assert stats["gathers"] > 0, stats

        # (2) coverage: the ledger explains the window, idle included
        report = attribution.bottleneck_report()
        assert report["coverage"] >= 0.9, (
            f"attribution coverage {report['coverage']} < 0.9: "
            f"{report['entries']}"
        )
        components = {e["component"] for e in report["entries"]}
        assert "idle" in components, (
            f"instrumented worker idle missing from the ledger: "
            f"{sorted(components)}"
        )
        idle_s = next(
            e["seconds"] for e in report["entries"]
            if e["component"] == "idle"
        )
        assert idle_s > 0.0
    finally:
        server.stop()


def _placement_map(config, nodes, jobs):
    """Run ``jobs`` serially through a fresh server built from ``config``
    and return {(job_id, alloc name) -> node_id}. Serial registration
    (wait for each job to place) keeps both servers' scheduling
    snapshots identical so the maps are comparable bit-for-bit."""
    server = Server(config)
    server.start()
    try:
        for n in nodes:
            server.raft_apply(NODE_REGISTER, copy.deepcopy(n))
        out = {}
        for tpl in jobs:
            j = copy.deepcopy(tpl)
            server.register_job(j)
            wait_for(
                lambda: len(server.fsm.state.allocs_by_job(
                    j.namespace, j.id, True)) >= j.task_groups[0].count,
                timeout=60.0, msg=f"{j.id} placed",
            )
            for a in server.fsm.state.allocs_by_job(j.namespace, j.id, True):
                out[(a.job_id, a.name)] = a.node_id
        return out
    finally:
        server.stop()


def test_device_path_matches_host_oracle_end_to_end():
    """Placement-map parity at the SERVER level: the same nodes and jobs
    through the batched device path and through the pure-host path
    (device_batch=0) must land every allocation on the same node.
    ring_decorrelate is off on both sides because the per-eval ring
    rotation keys on eval IDs, which necessarily differ across servers."""
    nodes = [mini_node(i) for i in range(20)]
    jobs = [mini_job(f"parity-{i}", count=25) for i in range(8)]

    device_cfg = ServerConfig(
        num_schedulers=2, deterministic=True, device_batch=4,
        device_min_placements=0, ring_decorrelate=False,
        heartbeat_min_ttl=3600, heartbeat_max_ttl=7200,
    )
    host_cfg = ServerConfig(
        num_schedulers=2, deterministic=True, device_batch=0,
        ring_decorrelate=False,
        heartbeat_min_ttl=3600, heartbeat_max_ttl=7200,
    )

    via_device = _placement_map(device_cfg, nodes, jobs)
    via_host = _placement_map(host_cfg, nodes, jobs)

    assert len(via_device) == sum(j.task_groups[0].count for j in jobs)
    assert via_device == via_host, (
        "device path diverged from host oracle: "
        f"{sorted(set(via_device.items()) ^ set(via_host.items()))[:10]}"
    )
