"""Wire raft tests: election, replication, recovery, snapshot install.

Covers the consensus slot (reference vendored hashicorp/raft,
nomad/server.go:1079): multi-node clusters over real loopback TCP — the
reference's in-process multi-server strategy (nomad/testing.go joining N
TestServers, SURVEY §4.2).
"""
import shutil
import tempfile
import time

import pytest

from nomad_tpu import mock
from nomad_tpu.rpc.transport import RPCServer
from nomad_tpu.server.fsm import JOB_REGISTER, NODE_REGISTER, NomadFSM
from nomad_tpu.server.raft import NotLeaderError
from nomad_tpu.server.wire_raft import LEADER, WireRaft, WireRaftConfig


def fast_config(node_id: str) -> WireRaftConfig:
    return WireRaftConfig(
        node_id=node_id,
        election_timeout_min=0.15,
        election_timeout_max=0.3,
        heartbeat_interval=0.03,
        rpc_timeout=0.5,
        apply_timeout=5.0,
    )


def wait_until(fn, timeout=8.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if fn():
            return
        time.sleep(0.02)
    raise AssertionError(f"timed out waiting for {msg}")


class Node:
    """One raft participant with its own RPC endpoint and FSM."""

    def __init__(self, node_id: str, data_dir=None):
        self.node_id = node_id
        self.rpc = RPCServer()
        self.fsm = NomadFSM()
        self.data_dir = data_dir
        self.raft = None

    def wire(self, all_nodes, start=True):
        peers = {
            n.node_id: n.rpc.addr for n in all_nodes if n.node_id != self.node_id
        }
        self.raft = WireRaft(
            self.rpc, peers, fast_config(self.node_id), data_dir=self.data_dir
        )
        self.raft.join(self.fsm)
        self.rpc.start()
        if start:
            self.raft.start()
        return self

    def stop(self):
        if self.raft is not None:
            self.raft.close()
        self.rpc.stop()


@pytest.fixture
def cluster():
    nodes = []

    def make(n, data_dirs=None, defer=()):
        for i in range(n):
            nodes.append(Node(f"n{i}", data_dirs[i] if data_dirs else None))
        for node in nodes:
            node.wire(nodes, start=node.node_id not in defer)
        return nodes

    yield make
    for node in nodes:
        node.stop()


def leader_of(nodes):
    leaders = [n for n in nodes if n.raft.state == LEADER]
    return leaders[0] if len(leaders) == 1 else None


class TestWireRaft:
    def test_single_leader_elected(self, cluster):
        nodes = cluster(3)
        wait_until(lambda: leader_of(nodes) is not None, msg="leader election")
        leader = leader_of(nodes)
        # followers agree on who leads
        wait_until(
            lambda: all(
                n.raft.leader_id == leader.node_id for n in nodes
            ),
            msg="leader agreement",
        )

    def test_replication_to_all_fsms(self, cluster):
        nodes = cluster(3)
        wait_until(lambda: leader_of(nodes) is not None)
        leader = leader_of(nodes)
        node = mock.node()
        index, _ = leader.raft.apply(0, NODE_REGISTER, node)
        assert index > 0
        wait_until(
            lambda: all(
                n.fsm.state.node_by_id(node.id) is not None for n in nodes
            ),
            msg="replication to all FSMs",
        )

    def test_follower_rejects_apply(self, cluster):
        nodes = cluster(3)
        wait_until(lambda: leader_of(nodes) is not None)
        follower = next(n for n in nodes if n.raft.state != LEADER)
        with pytest.raises(NotLeaderError):
            follower.raft.apply(0, NODE_REGISTER, mock.node())

    def test_leader_failover(self, cluster):
        nodes = cluster(3)
        wait_until(lambda: leader_of(nodes) is not None)
        leader = leader_of(nodes)
        n1 = mock.node()
        leader.raft.apply(0, NODE_REGISTER, n1)

        leader.stop()
        rest = [n for n in nodes if n is not leader]
        wait_until(lambda: leader_of(rest) is not None, msg="re-election")
        new_leader = leader_of(rest)
        assert new_leader is not leader
        # old entry survived, new applies work
        assert new_leader.fsm.state.node_by_id(n1.id) is not None
        n2 = mock.node()
        new_leader.raft.apply(0, NODE_REGISTER, n2)
        wait_until(
            lambda: all(
                n.fsm.state.node_by_id(n2.id) is not None for n in rest
            ),
            msg="post-failover replication",
        )

    def test_late_follower_catches_up(self, cluster):
        nodes = cluster(3, defer=("n2",))
        active = nodes[:2]
        late = nodes[2]
        wait_until(lambda: leader_of(active) is not None)
        leader = leader_of(active)
        registered = [mock.node() for _ in range(5)]
        for n in registered:
            leader.raft.apply(0, NODE_REGISTER, n)
        # now the laggard starts participating
        late.raft.start()
        wait_until(
            lambda: all(
                late.fsm.state.node_by_id(n.id) is not None for n in registered
            ),
            msg="late follower catch-up",
        )

    def test_snapshot_install_for_compacted_follower(self, cluster):
        nodes = cluster(3, defer=("n2",))
        active = nodes[:2]
        late = nodes[2]
        wait_until(lambda: leader_of(active) is not None)
        leader = leader_of(active)
        registered = [mock.node() for _ in range(5)]
        for n in registered:
            leader.raft.apply(0, NODE_REGISTER, n)
        job = mock.job()
        leader.raft.apply(0, JOB_REGISTER, job)
        # compact the leader's log so the laggard can't be served entries
        snap_index = leader.raft.snapshot(0)
        assert snap_index > 0
        assert leader.raft._entries_from(1) is None
        late.raft.start()
        wait_until(
            lambda: late.fsm.state.job_by_id("default", job.id) is not None
            and all(late.fsm.state.node_by_id(n.id) is not None for n in registered),
            msg="snapshot install",
        )

    def test_snapshot_blob_is_codec_not_pickle(self):
        """InstallSnapshot ships msgpack through the typed codec — never
        pickle, which would hand code execution to any peer reaching the
        RPC port (ADVICE r1). Round-trips every state table including ACL
        and autopilot entries."""
        import pickle

        from nomad_tpu.server import wire_raft as wr
        from nomad_tpu.state import StateStore
        from nomad_tpu.structs.acl import ACLPolicy, ACLToken

        store = StateStore()
        n = mock.node()
        store.upsert_node(1, n)
        j = mock.job()
        store.upsert_job(2, j)
        store.upsert_acl_policies(3, [ACLPolicy(
            name="readonly", rules='namespace "default" { policy = "read" }'
        )])
        tok = ACLToken(name="t", type="client", policies=["readonly"])
        store.upsert_acl_tokens(4, [tok])

        blob = wr._encode_fsm_state(store.snapshot())
        # a pickle payload must NOT be interpretable by the decode path
        with pytest.raises(Exception):
            wr._decode_fsm_state(pickle.dumps({"__reduce__": "nope"}))

        restored = wr._decode_fsm_state(blob)
        assert restored.node_by_id(n.id).name == n.name
        assert restored.job_by_id("default", j.id).id == j.id
        assert restored.acl_policies_table["readonly"].rules
        assert restored.acl_token_by_accessor(tok.accessor_id).name == "t"
        assert restored.latest_index == store.latest_index
        # pickle survives only in the legacy local-disk fallback — never
        # on any path that touches wire bytes
        import inspect

        for fn in (wr._encode_fsm_state, wr._decode_fsm_state,
                   wr.WireRaft._handle_install_snapshot,
                   wr.WireRaft._handle_append_entries,
                   wr.WireRaft._append_locked,
                   wr.WireRaft.snapshot):
            src = inspect.getsource(fn)
            for needle in ("import pickle", "pickle.loads", "pickle.dumps"):
                assert needle not in src, f"{fn.__name__}: {needle}"

    def test_restart_recovers_from_disk(self):
        tmp = tempfile.mkdtemp(prefix="wire-raft-")
        try:
            node = Node("solo", data_dir=tmp).wire([])
            wait_until(lambda: node.raft.state == LEADER, msg="solo leader")
            registered = [mock.node() for _ in range(3)]
            for n in registered:
                node.raft.apply(0, NODE_REGISTER, n)
            term_before = node.raft.current_term
            node.stop()

            node2 = Node("solo", data_dir=tmp).wire([])
            wait_until(lambda: node2.raft.state == LEADER, msg="solo re-leader")
            assert node2.raft.current_term >= term_before
            for n in registered:
                assert node2.fsm.state.node_by_id(n.id) is not None, "log replay"
            node2.stop()
        finally:
            shutil.rmtree(tmp, ignore_errors=True)


class TestServerOnWireRaft:
    def test_three_servers_schedule_and_replicate(self):
        """Three Server processes-worth of runtime on wire raft: writes on
        the leader replicate; the leader's scheduler places allocs; the
        follower FSMs see them (reference: FSM on every server,
        fsm.go:173)."""
        from nomad_tpu.server.server import Server, ServerConfig

        rpcs = [RPCServer() for _ in range(3)]
        rafts = []
        for i, rpc in enumerate(rpcs):
            peers = {
                f"s{j}": rpcs[j].addr for j in range(3) if j != i
            }
            rafts.append(WireRaft(rpc, peers, fast_config(f"s{i}")))
        servers = [
            Server(ServerConfig(num_schedulers=1, deterministic=True),
                   raft=rafts[i], name=f"s{i}")
            for i in range(3)
        ]
        try:
            for rpc in rpcs:
                rpc.start()
            for s in servers:
                s.start()
            for r in rafts:
                r.start()
            wait_until(
                lambda: sum(1 for r in rafts if r.state == LEADER) == 1,
                msg="server leader",
            )
            leader = next(s for s, r in zip(servers, rafts) if r.state == LEADER)
            followers = [s for s in servers if s is not leader]

            leader.register_node(mock.node())
            leader.register_node(mock.node())
            job = mock.job()
            leader.register_job(job)
            wait_until(
                lambda: len(leader.fsm.state.allocs_by_job("default", job.id, True)) == 10,
                timeout=30,
                msg="placement on leader",
            )
            wait_until(
                lambda: all(
                    len(f.fsm.state.allocs_by_job("default", job.id, True)) == 10
                    for f in followers
                ),
                msg="alloc replication to followers",
            )
        finally:
            for s in servers:
                s.stop()
            for r in rafts:
                r.close()
            for rpc in rpcs:
                rpc.stop()


class TestAgentsOnWireRaft:
    def test_three_agent_cluster_bootstrap_and_write(self):
        """Three full agents with gossip + wire raft: membership converges,
        raft bootstraps at expect=3, exactly one leader emerges, and a
        write through any agent's RPC lands on every FSM."""
        from nomad_tpu.agent.agent import Agent, AgentConfig
        from nomad_tpu.rpc.transport import RPCClient
        from nomad_tpu.server.wire_raft import WireRaftConfig

        agents = []
        try:
            for i in range(3):
                cfg = AgentConfig(
                    name=f"a{i}", server_enabled=True, wire_raft=True,
                    bootstrap_expect=3, num_schedulers=0,
                )
                a = Agent(cfg)
                # speed up elections for the test
                a.wire_raft.config = WireRaftConfig(
                    node_id=a.wire_raft.node_id,
                    election_timeout_min=0.15, election_timeout_max=0.3,
                    heartbeat_interval=0.03, rpc_timeout=0.5,
                )
                agents.append(a)
            agents[0].start()
            seed = "{}:{}".format(*agents[0].membership.gossip_addr)
            for a in agents[1:]:
                a.config.retry_join = [seed]
                a.start()
            wait_until(
                lambda: all(a._raft_started for a in agents),
                msg="raft bootstrap at expect=3",
            )
            wait_until(
                lambda: sum(1 for a in agents if a.server.is_leader) == 1,
                msg="single leader among agents",
            )
            # gossip leader tag → follower forwarding works
            leader = next(a for a in agents if a.server.is_leader)
            follower = next(a for a in agents if not a.server.is_leader)
            wait_until(
                lambda: follower.rpc.leader_addr == leader.rpc.addr,
                msg="leader tag propagated",
            )
            node = mock.node()
            cli = RPCClient(*follower.rpc.addr)
            cli.call("Node.Register", node)
            wait_until(
                lambda: all(
                    a.server.fsm.state.node_by_id(node.id) is not None
                    for a in agents
                ),
                msg="write replicated to every agent FSM",
            )
            cli.close()
        finally:
            for a in agents:
                a.shutdown()


class TestReplicatedPeerRemoval:
    def test_remove_peer_replicated_shrinks_all_views(self, cluster):
        """Autopilot-style removal goes through the log: every replica's
        peer set shrinks, not just the leader's."""
        nodes = cluster(3)
        wait_until(lambda: leader_of(nodes) is not None)
        leader = leader_of(nodes)
        followers = [n for n in nodes if n is not leader]
        victim = followers[0]
        victim.stop()
        leader.raft.remove_peer_replicated(victim.node_id)
        survivor = followers[1]
        wait_until(
            lambda: victim.node_id not in leader.raft.peers
            and victim.node_id not in survivor.raft.peers,
            msg="peer removed on every replica",
        )
        # the shrunken cluster still commits
        n = mock.node()
        leader.raft.apply(0, NODE_REGISTER, n)
        wait_until(lambda: survivor.fsm.state.node_by_id(n.id) is not None,
                   msg="post-removal commit")


class TestStagedMembership:
    """Log-replicated peer ADDITION (the reference gets staged
    nonvoter->voter configuration changes from vendored hashicorp/raft,
    used at leader.go:859): adds commit through the log, so every
    replica grows its configuration at the same position and a minority
    partition can never grow its own voter set."""

    @staticmethod
    def _sever(node, peer_id):
        """Cut node's OUTBOUND RPC to peer_id; returns a restore fn."""
        from nomad_tpu.rpc.transport import RPCError

        orig = node.raft._client

        def gated(pid, _orig=orig):
            if pid == peer_id:
                raise RPCError("partitioned")
            return _orig(pid)

        node.raft._client = gated
        return lambda: setattr(node.raft, "_client", orig)

    def test_staged_add_promotes_to_voter(self, cluster):
        nodes = cluster(3)
        wait_until(lambda: leader_of(nodes) is not None, msg="leader")
        leader = leader_of(nodes)
        leader.raft.apply(0, NODE_REGISTER, mock.node())

        # a fourth server appears (gossip handed it the current peer map)
        d = Node("n3")
        nodes.append(d)  # fixture cleanup
        d.wire(nodes[:3] + [d])
        assert leader.raft.add_peer_staged("n3", d.rpc.addr)

        # every replica (the new one included) converges on a 4-server
        # VOTER configuration
        wait_until(
            lambda: all(
                len(n.raft.peers) == 3
                and not n.raft.nonvoters
                and not n.raft._self_nonvoter
                for n in nodes
            ),
            timeout=12, msg="staged add promoted everywhere",
        )
        # the new voter has the replicated state
        wait_until(lambda: len(d.fsm.state.nodes()) == 1, msg="catch-up")

    def test_add_during_partition_heals_to_single_config(self, cluster):
        nodes = cluster(3)
        wait_until(lambda: leader_of(nodes) is not None, msg="leader")
        leader = leader_of(nodes)
        victim = next(n for n in nodes if n.raft.state != LEADER)
        others = [n for n in nodes if n is not victim]

        # full partition: victim <-/-> {others}
        restores = []
        for other in others:
            restores.append(self._sever(other, victim.node_id))
            restores.append(self._sever(victim, other.node_id))

        # add a fourth server while partitioned: commits on the majority
        d = Node("n3")
        nodes.append(d)
        d.wire(nodes[:3] + [d])
        restores.append(self._sever(victim, "n3"))
        restores.append(self._sever(d, victim.node_id))
        assert leader.raft.add_peer_staged("n3", d.rpc.addr)
        majority = others + [d]
        wait_until(
            lambda: all(
                "n3" in (set(n.raft.peers) | {n.node_id})
                and not n.raft.nonvoters
                for n in majority
            ),
            timeout=12, msg="add committed on the majority side",
        )
        # the minority never learned the add, and CANNOT stage one itself
        assert "n3" not in victim.raft.peers
        assert victim.raft.add_peer_staged("n3", d.rpc.addr) is False
        assert "n3" not in victim.raft.peers

        # heal: the victim converges onto the SAME single configuration
        for restore in restores:
            restore()
        wait_until(
            lambda: set(victim.raft.peers) | {victim.node_id}
            == {"n0", "n1", "n2", "n3"}
            and not victim.raft.nonvoters,
            timeout=12, msg="healed minority adopts the replicated config",
        )
        # exactly one leader across the healed 4-voter cluster, and writes
        # replicate everywhere (no split quorum)
        wait_until(lambda: leader_of(nodes) is not None, timeout=12,
                   msg="single leader after heal")
        final_leader = leader_of(nodes)
        marker = mock.node()
        final_leader.raft.apply(0, NODE_REGISTER, marker)
        wait_until(
            lambda: all(
                n.fsm.state.node_by_id(marker.id) is not None for n in nodes
            ),
            timeout=12, msg="post-heal replication to all four",
        )

    def test_snapshot_carries_membership_config(self, cluster):
        """A follower caught up via InstallSnapshot past compacted
        PEER_ADD entries must still learn the added peer — membership
        rides the snapshot (hashicorp/raft keeps config in snapshot
        meta)."""
        nodes = cluster(3)
        wait_until(lambda: leader_of(nodes) is not None, msg="leader")
        leader = leader_of(nodes)
        victim = next(n for n in nodes if n.raft.state != LEADER)
        others = [n for n in nodes if n is not victim]

        restores = []
        for other in others:
            restores.append(self._sever(other, victim.node_id))
            restores.append(self._sever(victim, other.node_id))

        # add + promote a fourth server while the victim is partitioned
        d = Node("n3")
        nodes.append(d)
        d.wire(nodes[:3] + [d])
        restores.append(self._sever(victim, "n3"))
        restores.append(self._sever(d, victim.node_id))
        assert leader.raft.add_peer_staged("n3", d.rpc.addr)
        majority = others + [d]
        wait_until(
            lambda: all(not n.raft.nonvoters and "n3" in
                        (set(n.raft.peers) | {n.node_id}) for n in majority),
            timeout=12, msg="staged add committed+promoted",
        )
        for _ in range(3):
            leader2 = leader_of(majority)
            leader2.raft.apply(0, NODE_REGISTER, mock.node())
        # compact: the PEER_ADD entries disappear from the log
        leader2 = leader_of(majority)
        assert leader2.raft.snapshot() > 0
        leader2.raft.apply(0, NODE_REGISTER, mock.node())

        for restore in restores:
            restore()
        # the victim catches up via InstallSnapshot and STILL learns n3
        wait_until(
            lambda: "n3" in victim.raft.peers and not victim.raft.nonvoters,
            timeout=12, msg="snapshot-installed config includes the add",
        )
        wait_until(
            lambda: len(victim.fsm.state.nodes()) == len(leader2.fsm.state.nodes()),
            timeout=12, msg="victim state caught up",
        )
