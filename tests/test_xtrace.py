"""nomad-xtrace tests: cross-process trace context, RPC telemetry, the
log-bucketed histogram, and the multi-process stitcher.

Covers the full carrier chain — TraceContext on the RPC envelope,
client/server span pairing, ``Evaluation.trace_ctx`` riding the codec,
the ``Trace.Export`` cursor drain — plus the collector side: stitching
determinism, NTP-style clock-offset recovery against a planted skew,
and mandatory orphan degradation when a replica's spans never arrive.
"""
import random
import threading

import pytest

from nomad_tpu import mock
from nomad_tpu.rpc import RPCClient, RPCServer, bind_server, decode, encode
from nomad_tpu.rpc import transport
from nomad_tpu.server import InProcRaft, Server, ServerConfig
from nomad_tpu.structs.structs import Evaluation
from nomad_tpu.trace import attribution, stitch
from nomad_tpu.trace import context as xtrace
from nomad_tpu.utils.metrics import InmemSink, LogHistogram


@pytest.fixture(autouse=True)
def _clean_trace_state():
    xtrace.reset()
    transport.reset_rpc_stats()
    yield
    xtrace.reset()
    transport.reset_rpc_stats()


# ---------------------------------------------------------------------------
# LogHistogram
# ---------------------------------------------------------------------------


def test_histogram_percentile_within_bucket_factor():
    h = LogHistogram()
    values = [0.3, 1.5, 7.0, 40.0, 900.0, 900.0, 900.0, 12_000.0]
    for v in values:
        h.add(v)
    assert h.count == len(values)
    # log2 buckets: the reported percentile is within a factor of 2
    p50 = h.percentile(0.5)
    assert 7.0 / 2 <= p50 <= 40.0 * 2
    p99 = h.percentile(0.99)
    assert 12_000.0 / 2 <= p99 <= 12_000.0 * 2


def test_histogram_merge_equals_combined():
    a, b, both = LogHistogram(), LogHistogram(), LogHistogram()
    for i in range(1, 200):
        v = i * 0.7
        (a if i % 2 else b).add(v)
        both.add(v)
    a.merge(b)
    assert a.count == both.count
    assert a.to_wire() == both.to_wire()
    for q in (0.5, 0.9, 0.99):
        assert a.percentile(q) == both.percentile(q)


def test_histogram_wire_roundtrip_and_extremes():
    h = LogHistogram()
    h.add(0.0)          # underflow bucket
    h.add(1e-12)        # underflow bucket
    h.add(2.0 ** 40)    # overflow bucket
    rebuilt = LogHistogram(h.to_wire())
    assert rebuilt.count == 3
    assert rebuilt.to_wire() == h.to_wire()
    # overflow percentile reports the overflow bound, not garbage
    assert rebuilt.percentile(1.0) == 2.0 ** (LogHistogram.MAX_EXP + 1)


def test_histogram_concurrent_adds_under_witness():
    """The histogram is documented unsynchronized — embedders hold their
    own lock. Drive the real embedder (_record_dispatch under _rpc_lock,
    which then publishes through the metrics sink lock) from N threads
    with the runtime lock witness armed: every add lands and no lock-
    order violation is recorded."""
    from nomad_tpu.utils import lock_witness as _lw

    witness = _lw.arm()
    try:
        n_threads, per_thread = 8, 200

        def pound(tid):
            for i in range(per_thread):
                transport._record_dispatch(
                    "Witness.test", 0.001 * ((tid + i) % 7 + 1), None)

        threads = [threading.Thread(target=pound, args=(t,))
                   for t in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        st = witness.stats()
        assert st["violations"] == 0
        row = transport.rpc_stats()["Witness.test"]
        assert row["calls"] == n_threads * per_thread
    finally:
        _lw.disarm()


def test_prometheus_exposition_has_le_buckets():
    s = InmemSink(interval=100)
    for v in (0.5, 3.0, 3.0, 50.0):
        s.add_sample("nomad.rpc.Ping.latency_ms", v)
    text = s.prometheus()
    assert "# TYPE nomad_rpc_Ping_latency_ms histogram" in text
    assert 'nomad_rpc_Ping_latency_ms_bucket{le="+Inf"} 4' in text
    # cumulative counts are monotone over the le-labeled lines
    cums = [int(line.rsplit(" ", 1)[1]) for line in text.splitlines()
            if line.startswith("nomad_rpc_Ping_latency_ms_bucket")]
    assert cums == sorted(cums) and cums[-1] == 4
    assert "nomad_rpc_Ping_latency_ms_sum" in text
    assert "nomad_rpc_Ping_latency_ms_count 4" in text


# ---------------------------------------------------------------------------
# trace context: propagation, span ring, export cursor
# ---------------------------------------------------------------------------


def test_span_nesting_and_ambient_context():
    with xtrace.span("outer") as _:
        outer = xtrace.current()
        with xtrace.span("inner"):
            inner = xtrace.current()
            assert inner.trace_id == outer.trace_id
            assert inner.parent_id == outer.span_id
    spans = {s["name"]: s for s in xtrace.snapshot()}
    assert spans["inner"]["parent_id"] == spans["outer"]["span_id"]
    assert spans["inner"]["trace_id"] == spans["outer"]["trace_id"]
    assert spans["outer"]["parent_id"] is None


def test_activate_carries_wire_context():
    token = xtrace.activate({"trace_id": "t" * 16, "span_id": "s" * 16})
    try:
        ctx = xtrace.inject()
        assert ctx == {"trace_id": "t" * 16, "span_id": "s" * 16}
        with xtrace.span("child"):
            pass
    finally:
        xtrace.deactivate(token)
    assert xtrace.inject() is None
    (child,) = xtrace.snapshot()
    assert child["trace_id"] == "t" * 16
    assert child["parent_id"] == "s" * 16


def test_export_cursor_is_incremental_and_idempotent():
    for i in range(5):
        xtrace.record_span(f"s{i}", 0.0, 1.0)
    first = xtrace.export()
    assert [s["name"] for s in first["spans"]] == [f"s{i}" for i in range(5)]
    cursor = first["next_seq"]
    assert xtrace.export(after_seq=cursor)["spans"] == []
    xtrace.record_span("late", 1.0, 2.0)
    second = xtrace.export(after_seq=cursor)
    assert [s["name"] for s in second["spans"]] == ["late"]
    # re-polling the same cursor never double-counts
    again = xtrace.export(after_seq=cursor)
    assert [s["name"] for s in again["spans"]] == ["late"]


def test_error_spans_tag_exception_type():
    with pytest.raises(ValueError):
        with xtrace.span("boom"):
            raise ValueError("nope")
    (s,) = xtrace.snapshot()
    assert s["attrs"]["error"] == "ValueError"


# ---------------------------------------------------------------------------
# RPC layer: envelope propagation, per-method stats, frame errors
# ---------------------------------------------------------------------------


def test_rpc_call_links_client_and_server_spans():
    rpc = RPCServer()
    rpc.register("Math.add", lambda a, b: a + b)
    rpc.start()
    try:
        c = RPCClient(*rpc.addr)
        with xtrace.span("driver.op"):
            assert c.call("Math.add", 2, 3) == 5
        c.close()
    finally:
        rpc.stop()
    spans = {s["name"]: s for s in xtrace.snapshot()}
    root = spans["driver.op"]
    client = spans["rpc.client.Math.add"]
    server = spans["rpc.server.Math.add"]
    assert client["trace_id"] == server["trace_id"] == root["trace_id"]
    assert client["parent_id"] == root["span_id"]
    assert server["parent_id"] == client["span_id"]
    assert client["kind"] == "client" and server["kind"] == "server"
    assert client["attrs"]["req_bytes"] > 0
    # server span must nest inside the client span (same process, same
    # clock — true nesting, no skew)
    assert client["start"] <= server["start"] <= server["end"] <= client["end"]


def test_rpc_stats_table_and_unknown_methods_unrecorded():
    rpc = RPCServer()
    rpc.register("Math.add", lambda a, b: a + b)
    rpc.start()
    try:
        c = RPCClient(*rpc.addr)
        for i in range(3):
            c.call("Math.add", i, i)
        with pytest.raises(Exception):
            c.call("Totally.bogus")
        c.close()
    finally:
        rpc.stop()
    table = transport.rpc_stats(wire=True)
    assert set(table) == {"Math.add"}   # bogus methods never enter
    row = table["Math.add"]
    assert row["calls"] == 3 and row["errors"] == 0
    assert row["req_bytes"] > 0 and row["resp_bytes"] > 0
    assert row["latency_ms_p99"] >= row["latency_ms_p50"] > 0
    assert sum(row["latency_hist"]) == 3


def test_merge_rpc_tables_recomputes_percentiles():
    fast, slow = LogHistogram(), LogHistogram()
    for _ in range(90):
        fast.add(1.0)
    for _ in range(10):
        slow.add(4000.0)
    merged = transport.merge_rpc_tables([
        {"M.x": {"calls": 90, "errors": 0, "not_leader": 0,
                 "req_bytes": 10, "resp_bytes": 10,
                 "latency_hist": fast.to_wire()}},
        {"M.x": {"calls": 10, "errors": 1, "not_leader": 1,
                 "req_bytes": 5, "resp_bytes": 5,
                 "latency_hist": slow.to_wire()}},
    ])
    row = merged["M.x"]
    assert row["calls"] == 100 and row["errors"] == 1
    assert row["req_bytes"] == 15
    # one slow replica still moves the merged tail
    assert row["latency_ms_p99"] >= 2000.0
    assert row["latency_ms_p50"] <= 2.0


def test_frame_errors_carry_method_and_peer_context():
    import socket

    a, b = socket.socketpair()
    b.close()
    with pytest.raises(transport.FrameError) as ei:
        transport._read_exact(a, 8, peer="1.2.3.4:99", what="resp header")
    a.close()
    msg = str(ei.value)
    assert "1.2.3.4:99" in msg and "resp header" in msg and "/8 bytes" in msg
    # FrameError stays a ConnectionError: every existing retry/failover
    # except-clause keeps catching it
    assert isinstance(ei.value, ConnectionError)


# ---------------------------------------------------------------------------
# Evaluation.trace_ctx: the eval payload carrier
# ---------------------------------------------------------------------------


def test_eval_stamps_and_carries_trace_ctx():
    with xtrace.span("submit"):
        ev = mock.eval()
        expected = xtrace.inject()
    assert ev.trace_ctx == expected
    # rides the codec (raft log / RPC body) unchanged
    assert decode(encode(ev)).trace_ctx == expected
    # copy preserves it
    assert ev.copy().trace_ctx == expected


def test_eval_outside_trace_has_none_ctx_and_derived_ids():
    from nomad_tpu.trace import lifecycle

    ev = mock.eval()
    assert ev.trace_ctx is None
    trace_id, parent = lifecycle.eval_trace_ids(ev.id, ev.trace_ctx)
    assert trace_id == ev.id.replace("-", "")[:16]
    assert parent is None
    # deterministic: same eval id -> same derived trace id
    assert (trace_id, parent) == lifecycle.eval_trace_ids(ev.id, None)


# ---------------------------------------------------------------------------
# Trace.Export endpoint over the wire
# ---------------------------------------------------------------------------


def test_trace_export_rpc_drains_ring_with_cursor():
    s = Server(ServerConfig(num_schedulers=0), raft=InProcRaft(), name="s1")
    rpc = RPCServer()
    bind_server(s, rpc)
    rpc.start()
    try:
        c = RPCClient(*rpc.addr)
        node = mock.node()
        c.call("Node.Register", node)
        out = c.call("Trace.Export", 0, no_forward=True)
        assert out["spans"], "ring should hold the Node.Register span"
        names = {sp["name"] for sp in out["spans"]}
        assert "rpc.server.Node.Register" in names
        assert "Node.Register" in out["rpc"]
        cursor = out["next_seq"]
        out2 = c.call("Trace.Export", cursor, no_forward=True)
        assert all(sp["seq"] > cursor for sp in out2["spans"])
        c.close()
    finally:
        rpc.stop()
        s.stop()


# ---------------------------------------------------------------------------
# stitching: merge determinism, clock skew, orphan degradation
# ---------------------------------------------------------------------------


def _mk(name, proc, sid, parent, a, b, kind="internal", trace="t1",
        attrs=None):
    return {"trace_id": trace, "span_id": sid, "parent_id": parent,
            "name": name, "kind": kind, "process": proc,
            "start": a, "end": b, "attrs": attrs or {}}


def _three_process_spans(skew=0.0):
    """driver -> s0 (forward) -> s1, with s1's clock shifted by skew."""
    return [
        _mk("event.submit", "driver", "d1", None, 0.0, 1.0),
        _mk("rpc.client.Job.Register", "driver", "c1", "d1",
            0.1, 0.9, kind="client"),
        _mk("rpc.server.Job.Register", "s0", "s1span", "c1",
            0.15, 0.85, kind="server"),
        _mk("rpc.client.Job.Register", "s0", "c2", "s1span",
            0.2, 0.8, kind="client"),
        _mk("rpc.server.Job.Register", "s1", "s2span", "c2",
            0.3 + skew, 0.7 + skew, kind="server"),
    ]


def test_stitch_merge_is_deterministic_and_dedups():
    spans = _three_process_spans()
    shuffled = list(spans)
    random.Random(7).shuffle(shuffled)
    # overlapping drains: every span delivered twice
    a = stitch.merge_spans([spans, shuffled])
    b = stitch.merge_spans([shuffled, spans])
    assert a == b
    assert len(a) == len(spans)


def test_stitch_recovers_planted_clock_offset():
    skew = 5.0
    out = stitch.stitch([_three_process_spans(skew=skew)])
    # s1's clock read 5s ahead; the estimator recovers it (driver is the
    # reference: most spans tie -> deterministic name tie-break picks it)
    off = out["clock_offsets_ms"]
    assert abs(off["s1"] - skew * 1000.0) < 1.0
    assert off["s0"] == 0.0
    (trace,) = out["traces"]
    assert trace["orphans"] == 0
    # after normalization the leaf nests inside its parent again
    by_name = {(s["process"], s["name"]): s for s in out["spans"]}
    leaf = by_name[("s1", "rpc.server.Job.Register")]
    hop = by_name[("s0", "rpc.client.Job.Register")]
    assert hop["start"] <= leaf["start"] <= leaf["end"] <= hop["end"]
    # the whole stitched trace spans one second, not six
    assert trace["duration_ms"] < 1500.0


def test_stitch_orphans_degrade_to_partial_tree():
    spans = _three_process_spans()
    # the middle process was SIGKILLed: its spans never exported
    survivors = [s for s in spans if s["process"] != "s0"]
    out = stitch.stitch([survivors])
    (trace,) = out["traces"]
    assert trace["orphans"] == 1   # s1's server span lost its parent
    assert trace["spans"] == len(survivors)
    text = stitch.format_tree(trace)
    assert "ORPHAN" in text
    # parent-pointer cycle (corrupt input) also degrades, never raises
    cyc = [_mk("a", "p", "x", "y", 0.0, 1.0), _mk("b", "p", "y", "x", 0.0, 1.0)]
    (t2,) = stitch.build_trees(cyc)
    assert t2["orphans"] == 2


# ---------------------------------------------------------------------------
# stitched attribution
# ---------------------------------------------------------------------------


def test_stitched_report_names_wire_components():
    spans = _three_process_spans() + [
        _mk("eval.queue_wait", "s1", "q1", None, 1.0, 2.0),
        _mk("eval.wait_min_index", "s1", "w1", None, 2.0, 2.5,
            attrs={"role": "follower"}),
        _mk("eval.invoke", "s1", "i1", None, 2.5, 4.0),
    ]
    rep = attribution.stitched_report(spans)
    comps = {e["component"]: e["seconds"] for e in rep["entries"]}
    # the follower->leader relay claims forward_hop; the driver's call
    # minus its matched server child claims rpc_wait
    assert comps["forward_hop"] > 0
    assert comps["rpc_wait"] > 0
    assert comps["follower_lag"] == pytest.approx(0.5)
    assert comps["invoke"] == pytest.approx(1.5)
    assert rep["coverage"] >= attribution.COVERAGE_FLOOR
    assert rep["coverage_ok"]
    assert rep["processes"] == ["driver", "s0", "s1"]


def test_stitched_report_unmatched_client_span_is_all_rpc_wait():
    spans = [
        _mk("rpc.client.Node.Heartbeat", "driver", "c1", None,
            0.0, 1.0, kind="client"),
    ]
    rep = attribution.stitched_report(spans)
    comps = {e["component"]: e["seconds"] for e in rep["entries"]}
    # the server died before exporting: the whole call reads as wire time
    assert comps["rpc_wait"] == pytest.approx(1.0)


def test_stitched_report_empty_and_coverage_floor():
    rep = attribution.stitched_report([])
    assert rep["top"] == "no spans recorded"
    assert not rep["entries"]
    # a span set with a huge intra-trace hole fails the self-check
    spans = [
        _mk("eval.invoke", "p", "a", None, 0.0, 1.0),
        _mk("eval.invoke", "p", "b", None, 99.0, 100.0),
    ]
    rep2 = attribution.stitched_report(spans)
    assert not rep2["coverage_ok"]
    assert "coverage" in rep2["top"]
