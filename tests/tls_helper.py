"""Test-only CA + cert mint via the openssl CLI (the reference's
helper/tlsutil test fixtures role)."""
from __future__ import annotations

import os
import subprocess


def make_cluster_certs(directory: str, names=("server", "client")) -> dict:
    """One CA and one signed cert per name. Returns
    {name: (ca, cert, key)} path tuples."""
    os.makedirs(directory, exist_ok=True)

    def run(*args):
        subprocess.run(args, check=True, capture_output=True, cwd=directory,
                       timeout=60)

    ca_key = os.path.join(directory, "ca.key")
    ca_crt = os.path.join(directory, "ca.crt")
    run("openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
        "-keyout", ca_key, "-out", ca_crt, "-days", "1",
        "-subj", "/CN=nomad-tpu-test-ca")
    out = {}
    for name in names:
        key = os.path.join(directory, f"{name}.key")
        csr = os.path.join(directory, f"{name}.csr")
        crt = os.path.join(directory, f"{name}.crt")
        ext = os.path.join(directory, f"{name}.ext")
        # role-named SAN: hostname pinning (verify_server_hostname)
        # matches "server.<region>.nomad" against the SAN, not the CN
        with open(ext, "w") as f:
            f.write(
                f"subjectAltName=DNS:{name}.global.nomad,"
                "DNS:localhost,IP:127.0.0.1\n"
            )
        run("openssl", "req", "-newkey", "rsa:2048", "-nodes",
            "-keyout", key, "-out", csr, "-subj", f"/CN={name}.global.nomad")
        run("openssl", "x509", "-req", "-in", csr, "-CA", ca_crt,
            "-CAkey", ca_key, "-CAcreateserial", "-out", crt, "-days", "1",
            "-extfile", ext)
        out[name] = (ca_crt, crt, key)
    return out
